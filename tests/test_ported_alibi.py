"""Migration proof #10: mechanical port of the reference test file
``/root/reference/tests/attention/test_alibi.py`` run against
``flashinfer_tpu``.

Same porting contract as tests/test_ported_batch_prefill.py: reference
parameter matrices verbatim, reference call sequences
(``single_decode_with_kv_cache(..., pos_encoding_mode="ALIBI")``,
``single_prefill_with_kv_cache(..., causal=, pos_encoding_mode="ALIBI")``),
torch.float16 -> jnp.float16.  Oracle = the reference's
``tests/test_helpers/alibi_reference.py`` (labml-derived slopes +
distance-bias attention) transcribed to numpy f64.

The reference's warmup_jit fixture (CUDA module prebuild) has no TPU
meaning and is dropped; XLA compiles on first call.  Work caps as in the
other ports (FLASHINFER_TPU_FULL_MATRIX=1 runs everything).
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import flashinfer_tpu as fi
from tests.test_ported_batch_prefill import _sample, _work_gate


def _get_slopes(n_heads):
    """Reference slopes (alibi_reference.py:21-58): geometric series from
    2^(-8/n) at the closest lower power of two, odd-step fill above."""
    n = 2 ** math.floor(math.log2(n_heads))
    m0 = 2.0 ** (-8.0 / n)
    m = m0 ** np.arange(1, 1 + n)
    if n < n_heads:
        mh0 = 2.0 ** (-4.0 / n)
        mh = mh0 ** np.arange(1, 1 + 2 * (n_heads - n), 2)
        m = np.concatenate([m, mh])
    return m.astype(np.float64)


def _alibi_attention(q, k, v, mask):
    """Reference oracle (alibi_reference.py:86-124) in f64 numpy: bias =
    key-distance * per-head slope, added AFTER the 1/sqrt(d) scale."""
    q = np.asarray(q, np.float64)
    k = np.asarray(k, np.float64)
    v = np.asarray(v, np.float64)
    q_len, num_heads, head_dim = q.shape
    scores = np.einsum("qhd,khd->qkh", q, k) / math.sqrt(head_dim)
    distance = np.arange(mask.shape[1], dtype=np.float64)[None, :]
    biases = distance[:, :, None] * _get_slopes(num_heads)[None, None, :]
    scores = scores + biases
    scores = np.where(mask[:, :, None], scores, -np.inf)
    m_ = scores.max(1, keepdims=True)
    e = np.exp(scores - m_)
    attn = e / e.sum(1, keepdims=True)
    return np.einsum("qkh,khd->qhd", attn, v)


@pytest.mark.parametrize(
    "seq_len,num_heads,head_dim",
    _sample("alibi_decode", [1, 9, 81, 729], [4, 8, 32], [128, 256]),
)
def test_single_decode_alibi(seq_len, num_heads, head_dim):
    """Reference test_single_decode_alibi (test_alibi.py:57)."""
    _work_gate(1, 1, seq_len, num_heads, head_dim)
    key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, (num_heads, head_dim), jnp.float16)
    k = jax.random.normal(
        jax.random.fold_in(key, 1), (seq_len, num_heads, head_dim),
        jnp.float16)
    v = jax.random.normal(
        jax.random.fold_in(key, 2), (seq_len, num_heads, head_dim),
        jnp.float16)
    o = fi.single_decode_with_kv_cache(q, k, v, pos_encoding_mode="ALIBI")
    mask = np.ones((1, seq_len), bool)
    o_ref = _alibi_attention(np.asarray(q, np.float32)[None], k, v, mask)[0]
    np.testing.assert_allclose(
        np.asarray(o, np.float32), o_ref, rtol=1e-3, atol=1e-3)


@pytest.mark.parametrize(
    "q_len,kv_len,num_heads,head_dim,causal",
    _sample(
        "alibi_prefill",
        [1, 17, 81, 987], [1, 17, 81, 987], [4, 8, 32], [128, 256],
        [False, True],
    ),
)
def test_single_prefill_alibi(q_len, kv_len, num_heads, head_dim, causal):
    """Reference test_single_prefill_alibi (test_alibi.py:76)."""
    if causal and q_len > kv_len:
        pytest.skip("Causal attention requires q_len <= kv_len")
    _work_gate(1, q_len, kv_len, num_heads, head_dim)
    key = jax.random.PRNGKey(1)
    q = jax.random.normal(key, (q_len, num_heads, head_dim), jnp.float16)
    k = jax.random.normal(
        jax.random.fold_in(key, 1), (kv_len, num_heads, head_dim),
        jnp.float16)
    v = jax.random.normal(
        jax.random.fold_in(key, 2), (kv_len, num_heads, head_dim),
        jnp.float16)
    o = fi.single_prefill_with_kv_cache(
        q, k, v, causal=causal, pos_encoding_mode="ALIBI")
    mask = np.ones((q_len, kv_len), bool)
    if causal:
        mask = np.tril(mask, k=kv_len - q_len)
    o_ref = _alibi_attention(q, k, v, mask)
    np.testing.assert_allclose(
        np.asarray(o, np.float32), o_ref, rtol=1e-2, atol=1e-2)
