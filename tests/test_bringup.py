"""Hardware graduation observatory tests (ISSUE 20).

The bring-up harness's whole value is what happens on a BAD day —
a Mosaic wedge mid-ladder — so the pins here run the ladder machinery
with injected runners/probers (no jax, no subprocesses) plus ONE real
subprocess wedge (the ``sim:wedge`` rung, which sleeps past its
timeout before importing anything heavy): exact-rung attribution,
quarantine emission, halt + pending remainder, ``--resume``
skip-completed semantics, chooser pruning of quarantined tactics, the
L006 measured-reference gate, and the journal ↔ banked-row join.

The full 29-rung interpret-mode ladder is exercised by
``obs bringup --selftest`` (the lint.yml gate), not here — tier-1 must
stay fast.
"""

import json
import os

import pytest

from flashinfer_tpu import tactics_blocklist
from flashinfer_tpu.obs import bringup


def _fake_rungs(n=4):
    return [{"rung_id": f"r{i}", "kind": "knob", "op": f"op{i}",
             "tactic": i, "driver": "rmsnorm",
             "bench_phases": [f"phase{i}"]} for i in range(n)]


def _runner_factory(calls, wedge_at=None, fail_at=None):
    def runner(rung, *, timeout_s, interpret, chip):
        calls.append(rung["rung_id"])
        if rung["rung_id"] == wedge_at:
            return {"outcome": "wedge", "wall_s": timeout_s,
                    "detail": "rung timed out (chip wedged?)"}
        if rung["rung_id"] == fail_at:
            return {"outcome": "fail", "wall_s": 0.1, "detail": "boom"}
        return {"outcome": "pass", "wall_s": 0.1, "detail": ""}
    return runner


def _healthy():
    return {"healthy": True, "elapsed": 0.1, "detail": "ok"}


def test_ladder_covers_every_registry_entry():
    rungs = bringup.build_ladder("v5e")
    assert bringup.coverage_problems(rungs) == []
    # the three registries each contribute: 18 mosaic_risks + 4
    # planners + 7 knob bindings
    kinds = {}
    for r in rungs:
        kinds[r["kind"]] = kinds.get(r["kind"], 0) + 1
    assert kinds == {"mosaic_risk": 18, "planner": 4, "knob": 7}
    # riskiest construct class first: every strided-lane rung precedes
    # every lane-slice rung precedes every cast rung
    order = [r["rule"] for r in rungs if r["kind"] == "mosaic_risk"]
    ranked = [bringup.RISK_ORDER[r] for r in order]
    assert ranked == sorted(ranked)


def test_wedge_attributes_quarantines_and_halts(tmp_path):
    journal = bringup.Journal(str(tmp_path / "j.jsonl"))
    qpath = str(tmp_path / "q.json")
    rungs = _fake_rungs(4)
    calls = []
    s = bringup.run_ladder(
        rungs, journal=journal, journal_id="jid-1", quarantine=qpath,
        runner=_runner_factory(calls, wedge_at="r1"), prober=_healthy,
        interpret=True, probe_every=0, verbose=False)
    # exact-rung attribution: r1 wedged, r0 passed, r2/r3 never ran
    assert s["wedged"] == ["r1"] and s["halted"]
    assert s["passed"] == 1 and s["pending"] == ["r2", "r3"]
    assert calls == ["r0", "r1"]
    q = json.loads(open(qpath).read())
    assert [e["rung_id"] for e in q] == ["r1"]
    # knob rungs carry the (op, tactic) pair and the poisoned phases
    assert q[0]["op"] == "op1" and q[0]["tactic"] == 1
    assert q[0]["bench_phases"] == ["phase1"]
    assert q[0]["journal_id"] == "jid-1"
    # journal: r2/r3 recorded pending, not silently dropped
    outcomes = journal.rung_outcomes()
    assert outcomes == {"r0": "pass", "r1": "wedge",
                        "r2": "pending", "r3": "pending"}


def test_resume_skips_passed_and_quarantined(tmp_path):
    journal = bringup.Journal(str(tmp_path / "j.jsonl"))
    qpath = str(tmp_path / "q.json")
    rungs = _fake_rungs(4)
    bringup.run_ladder(
        rungs, journal=journal, journal_id="jid-1", quarantine=qpath,
        runner=_runner_factory([], wedge_at="r1"), prober=_healthy,
        interpret=True, probe_every=0, verbose=False)
    # resume: r0 (passed) and r1 (quarantined) skipped, r2/r3 run
    calls = []
    s = bringup.run_ladder(
        rungs, journal=journal, journal_id="jid-1", quarantine=qpath,
        runner=_runner_factory(calls), prober=_healthy,
        interpret=True, probe_every=0, resume=True, verbose=False)
    assert calls == ["r2", "r3"]
    assert s["skipped"] == 2 and s["passed"] == 2 and not s["halted"]
    # a non-resume run re-runs everything but the quarantined rung
    calls2 = []
    bringup.run_ladder(
        rungs, journal=journal, journal_id="jid-2", quarantine=qpath,
        runner=_runner_factory(calls2), prober=_healthy,
        interpret=True, probe_every=0, resume=False, verbose=False)
    assert calls2 == ["r0", "r2", "r3"]


def test_unhealthy_probe_after_clean_rung_is_a_wedge(tmp_path):
    """A rung can exit 0 and still leave the chip wedged — the
    post-rung probe is the arbiter, and the wedge attributes to the
    rung that ran before it."""
    journal = bringup.Journal(str(tmp_path / "j.jsonl"))
    qpath = str(tmp_path / "q.json")
    probes = iter([_healthy(),
                   {"healthy": False, "elapsed": 0.1, "detail": "dead"}])
    s = bringup.run_ladder(
        _fake_rungs(3), journal=journal, journal_id="jid-1",
        quarantine=qpath, runner=_runner_factory([]),
        prober=lambda: next(probes), interpret=False, probe_every=1,
        verbose=False)
    assert s["wedged"] == ["r1"] and s["pending"] == ["r2"]
    assert [e["rung_id"] for e in json.loads(open(qpath).read())] == ["r1"]


def test_sim_wedge_subprocess_times_out(tmp_path):
    """The one real-subprocess pin: the sim rung sleeps past its
    timeout and _spawn_rung must kill it and report a wedge."""
    res = bringup._spawn_rung({"rung_id": bringup.SIM_WEDGE_RUNG},
                              timeout_s=3.0, interpret=True)
    assert res["outcome"] == "wedge"
    assert "timed out" in res["detail"]


def test_quarantined_tactic_pruned_from_choosers(tmp_path, monkeypatch):
    qpath = str(tmp_path / "q.json")
    open(qpath, "w").write(json.dumps([
        {"rung_id": "l009:decode.splits", "op": "decode.splits",
         "tactic": 4, "reason": "wedged", "journal_id": "jid-1"},
        {"rung_id": "l009:prefill.fused_ingest",
         "op": "prefill.fused_ingest", "tactic": "on",
         "reason": "wedged", "journal_id": "jid-1"},
    ]))
    monkeypatch.setenv("FLASHINFER_TPU_BRINGUP_QUARANTINE", qpath)
    monkeypatch.setattr(tactics_blocklist, "_bringup_cache", None)
    assert tactics_blocklist.blocked("decode.splits", 4)
    from flashinfer_tpu.obs import costmodel

    best, table = costmodel.choose_decode_splits(
        64, 4096, 32, 8, 128, hbm_tbps=0.8, candidates=(1, 2, 4))
    assert 4 not in table and {1, 2} <= set(table)
    use, ev = costmodel.predict_prefill_ingest_win(
        4096, 4096, 32, 8, 128, hbm_tbps=0.8)
    assert use is False and ev.get("pruned_quarantined") == 1.0
    # lifting the quarantine restores the candidate
    monkeypatch.delenv("FLASHINFER_TPU_BRINGUP_QUARANTINE")
    monkeypatch.setattr(tactics_blocklist, "_bringup_cache", None)
    _, table = costmodel.choose_decode_splits(
        64, 4096, 32, 8, 128, hbm_tbps=0.8, candidates=(1, 2, 4))
    assert 4 in table


def test_journal_joins_banked_rows_by_row_stamp():
    from flashinfer_tpu.obs import bench_audit

    row = {"phase": "decode_splits", "bs": 64, "ctx": 4096,
           "num_splits": 4, "us": 100.0, "tbps": 0.5}
    audited = bench_audit.RowAuditor().stamp(dict(row))
    # the stamp is derived from configuration identity only: recomputing
    # it over the stamped row (measurements and all) must round-trip
    assert audited["row_id"] == bench_audit.row_stamp(audited)
    assert audited["row_id"] == bench_audit.row_stamp(row)
    # measurement jitter does not move the join key
    noisy = dict(row, us=200.0, tbps=0.25)
    assert bench_audit.row_stamp(noisy) == audited["row_id"]
    # a different configuration does
    other = dict(row, num_splits=8)
    assert bench_audit.row_stamp(other) != audited["row_id"]


def test_graduate_flips_seed_to_measured(tmp_path):
    cfg_dir = tmp_path / "tuning_configs"
    cfg_dir.mkdir()
    key = "decode.splits|4096_256_32_8_128_16_16_bfloat16"
    (cfg_dir / "v5e.json").write_text(json.dumps({
        "decode": {"comment": "seeded", "seed": True,
                   "tactics": {key: 1, "decode.splits|other_shape": 1}},
    }))
    emit = tmp_path / "emit.json"
    emit.write_text(json.dumps({
        "decode": {"comment": "measured sweep", "seed": False,
                   "tactics": {key: 4}}}))
    banked = tmp_path / "BENCH_BANKED.md"
    row = {"phase": "decode_splits", "bs": 64, "num_splits": 4,
           "us": 50.0}
    banked.write_text("```json\n" + json.dumps({"rows": [row]})
                      + "\n```\n")
    journal = bringup.Journal(str(tmp_path / "j.jsonl"))
    g = bringup.graduate(
        [str(emit)], chip="v5e", journal=journal, journal_id="jid-9",
        configs_dir=str(cfg_dir), banked_path=str(banked))
    assert g["graduated"] == ["decode"]
    sec = json.loads((cfg_dir / "v5e.json").read_text())["decode"]
    from flashinfer_tpu.obs import bench_audit

    assert sec["provenance"] == "measured"
    assert sec["journal_id"] == "jid-9"
    assert sec["banked_row"] == [bench_audit.row_stamp(row)]
    assert "seed" not in sec
    # the measured winner replaced the seed value; the unmeasured key
    # survives and is labeled
    assert sec["tactics"][key] == 4
    assert sec["seed_keys"] == ["decode.splits|other_shape"]
    # journaled
    assert journal.step_outcomes("graduate") == {"decode": "pass"}


def test_graduate_refuses_without_banked_rows(tmp_path):
    cfg_dir = tmp_path / "tuning_configs"
    cfg_dir.mkdir()
    (cfg_dir / "v5e.json").write_text(json.dumps({
        "decode": {"seed": True, "tactics": {"decode.splits|s": 1}}}))
    emit = tmp_path / "emit.json"
    emit.write_text(json.dumps({
        "decode": {"tactics": {"decode.splits|s": 2}}}))
    banked = tmp_path / "BENCH_BANKED.md"
    banked.write_text("no rows here\n")
    g = bringup.graduate(
        [str(emit)], chip="v5e",
        journal=bringup.Journal(str(tmp_path / "j.jsonl")),
        journal_id="jid-9", configs_dir=str(cfg_dir),
        banked_path=str(banked))
    assert g["graduated"] == []
    assert g["skipped"] and "no banked rows" in g["skipped"][0]["reason"]
    # config untouched: an unauditable flip never lands
    sec = json.loads((cfg_dir / "v5e.json").read_text())["decode"]
    assert sec.get("seed") is True and "provenance" not in sec


def _staged_project(tmp_path, payload):
    from flashinfer_tpu.analysis.core import Project

    pkg = tmp_path / "pkg"
    (pkg / "tuning_configs").mkdir(parents=True)
    (pkg / "mod.py").write_text("x = 1\n")
    (pkg / "tuning_configs" / "gen.json").write_text(json.dumps(payload))
    return Project.from_paths([str(pkg)])


def test_l006_requires_references_on_measured_sections(tmp_path):
    from flashinfer_tpu.analysis import tuning_schema

    good = {"decode": {"provenance": "measured", "journal_id": "jid-1",
                       "banked_row": ["abc123def456"],
                       "tactics": {}}}
    assert tuning_schema.run(_staged_project(tmp_path, good)) == []
    for missing in ("journal_id", "banked_row"):
        bad = {"decode": dict(good["decode"])}
        del bad["decode"][missing]
        findings = tuning_schema.run(
            _staged_project(tmp_path / missing, bad))
        assert any(missing in f.message for f in findings), missing
    # empty reference list is as unfalsifiable as a missing one
    empty = {"decode": dict(good["decode"], banked_row=[])}
    findings = tuning_schema.run(_staged_project(tmp_path / "e", empty))
    assert any("banked_row" in f.message for f in findings)
    # seed sections need no references
    seed = {"decode": {"provenance": "seed", "tactics": {}}}
    assert tuning_schema.run(_staged_project(tmp_path / "s", seed)) == []


def test_record_phases_pending_journals_for_resume(tmp_path, monkeypatch):
    jpath = str(tmp_path / "j.jsonl")
    monkeypatch.setenv("FLASHINFER_TPU_BRINGUP_JOURNAL", jpath)
    probe = {"healthy": False, "detail": "dead"}
    bringup.record_phases_pending(["mla", "scans"], probe)
    j = bringup.Journal(jpath)
    assert j.step_outcomes("phase") == {"mla": "pending",
                                        "scans": "pending"}
    assert all(e["probe"] == probe for e in j.entries())


def test_quarantined_bench_phases_surface(tmp_path, monkeypatch):
    qpath = str(tmp_path / "q.json")
    open(qpath, "w").write(json.dumps([
        {"rung_id": "l015:cast:_mla_decode_kernel",
         "reason": "wedged", "bench_phases": ["mla"]},
        {"rung_id": "l009:decode.splits", "op": "decode.splits",
         "tactic": 4, "reason": "wedged",
         "bench_phases": ["decode_splits"]},
    ]))
    monkeypatch.setenv("FLASHINFER_TPU_BRINGUP_QUARANTINE", qpath)
    monkeypatch.setattr(tactics_blocklist, "_bringup_cache", None)
    try:
        assert sorted(bringup.quarantined_bench_phases()) == \
            ["decode_splits", "mla"]
    finally:
        monkeypatch.setattr(tactics_blocklist, "_bringup_cache", None)


def test_perf_report_graduation_section():
    from flashinfer_tpu.obs.roofline import (build_perf_report,
                                             render_perf_report)

    report = build_perf_report([])
    assert report["schema"] == "flashinfer_tpu.obs.perf/6"
    grad = report["graduation"]
    shipped = {(s["chip"], s["section"]) for s in grad["sections"]}
    assert ("v5e", "decode") in shipped
    assert all(s["status"] in ("pending", "measured", "quarantined")
               for s in grad["sections"])
    assert grad["audit"]["serving_ici"]["predicted_schema"] == "perf/2"
    assert "graduation (hardware bring-up pipeline)" \
        in render_perf_report(report)


@pytest.mark.quick
def test_doctor_summary_never_raises(tmp_path, monkeypatch):
    monkeypatch.setenv("FLASHINFER_TPU_BRINGUP_JOURNAL",
                       str(tmp_path / "nope" / "j.jsonl"))
    d = bringup.doctor_summary()
    assert d["journal_entries"] == 0 and d["session"] is None
    assert "v5e" in d["seed_sections_remaining"]
