"""Migration proof #4: mechanical port of the reference test file
``/root/reference/tests/attention/test_shared_prefix_kernels.py`` —
the cascade/shared-prefix stack end-to-end: append_paged_kv_cache +
get_batch_indices_positions + get_seq_lens build a two-region paged
cache, then MultiLevelCascadeAttentionWrapper (2 levels) must agree
with the LEGACY Batch*WithSharedPrefixPagedKVCacheWrapper
begin_forward/forward two-level path, plus the masked
merge_state_in_place semantics.

Deviations (written reasons):
- ``merge_state_in_place`` is FUNCTIONAL here (returns the merged
  (v, s) instead of mutating va/sa — jax arrays are immutable;
  docs/migration.md); the reference's aliasing assertions become
  return-value assertions.
- random-mask tries reduced 50 -> 8 (per-try invariants, split keys).
- matrix sampling: shared 1/48 rank sampler (FLASHINFER_TPU_FULL_MATRIX
  =1 for the reference's full cross-product).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import flashinfer_tpu as fi
from tests.test_ported_batch_prefill import _sample


def ceil_div(a, b):
    return (a + b - 1) // b


@pytest.mark.parametrize(
    "stage,batch_size,unique_kv_len,shared_kv_len,num_heads,causal,"
    "head_dim,page_size",
    _sample("shared_prefix", ["decode", "append"], [12, 17], [37, 17],
            [128, 512, 2048], [8, 16], [False], [128, 256], [1, 16],
            specials=[(0, "decode"), (0, "append")]),
)
def test_batch_attention_with_shared_prefix_paged_kv_cache(
    stage, batch_size, unique_kv_len, shared_kv_len, num_heads, causal,
    head_dim, page_size,
):
    """Reference test_batch_attention_with_shared_prefix_paged_kv_cache
    (test_shared_prefix_kernels.py:60-230)."""
    if stage == "decode" and causal:
        pytest.skip("Causal attention is not required in decode stage")
    assert shared_kv_len % page_size == 0
    kv_layout = "NHD"
    keys = jax.random.split(jax.random.PRNGKey(0), 5)
    if stage == "append":
        q = jax.random.normal(
            keys[0], (batch_size * unique_kv_len, num_heads, head_dim),
            jnp.float16)
        q_indptr = np.arange(0, batch_size + 1, dtype=np.int32) * \
            unique_kv_len
    else:
        q = jax.random.normal(
            keys[0], (batch_size, num_heads, head_dim), jnp.float16)
        q_indptr = np.arange(0, batch_size + 1, dtype=np.int32)
    k_shared = jax.random.normal(
        keys[1], (shared_kv_len, num_heads, head_dim), jnp.float16)
    v_shared = jax.random.normal(
        keys[2], (shared_kv_len, num_heads, head_dim), jnp.float16)
    k_unique = jax.random.normal(
        keys[3], (batch_size * unique_kv_len, num_heads, head_dim),
        jnp.float16)
    v_unique = jax.random.normal(
        keys[4], (batch_size * unique_kv_len, num_heads, head_dim),
        jnp.float16)

    total_pages = (ceil_div(shared_kv_len, page_size)
                   + batch_size * ceil_div(unique_kv_len, page_size))
    kv_data = jnp.zeros(
        (total_pages, 2, page_size, num_heads, head_dim), jnp.float16)

    shared_kv_indices = np.arange(
        0, ceil_div(shared_kv_len, page_size), dtype=np.int32)
    shared_append_indptr = np.arange(0, 2, dtype=np.int32) * shared_kv_len
    shared_kv_indptr = np.arange(0, 2, dtype=np.int32) * ceil_div(
        shared_kv_len, page_size)
    shared_last_page_len = np.full(
        (1,), (shared_kv_len - 1) % page_size + 1, dtype=np.int32)
    kv_data = fi.append_paged_kv_cache(
        k_shared, v_shared,
        *fi.get_batch_indices_positions(
            shared_append_indptr,
            fi.get_seq_lens(shared_kv_indptr, shared_last_page_len,
                            page_size),
            k_shared.shape[0]),
        kv_data, shared_kv_indices, shared_kv_indptr,
        shared_last_page_len, kv_layout,
    )
    unique_kv_indices = np.arange(
        0, batch_size * ceil_div(unique_kv_len, page_size),
        dtype=np.int32) + ceil_div(shared_kv_len, page_size)
    unique_append_indptr = np.arange(
        0, batch_size + 1, dtype=np.int32) * unique_kv_len
    unique_kv_indptr = np.arange(
        0, batch_size + 1, dtype=np.int32) * ceil_div(
        unique_kv_len, page_size)
    unique_last_page_len = np.full(
        (batch_size,), (unique_kv_len - 1) % page_size + 1,
        dtype=np.int32)
    kv_data = fi.append_paged_kv_cache(
        k_unique, v_unique,
        *fi.get_batch_indices_positions(
            unique_append_indptr,
            fi.get_seq_lens(unique_kv_indptr, unique_last_page_len,
                            page_size),
            k_unique.shape[0]),
        kv_data, unique_kv_indices, unique_kv_indptr,
        unique_last_page_len, kv_layout,
    )

    workspace = jnp.empty((32 * 1024 * 1024,), jnp.int8)
    multi_level_wrapper = fi.MultiLevelCascadeAttentionWrapper(
        2, workspace, kv_layout)
    qo_indptr_top = np.array([0, q.shape[0]], dtype=np.int32)
    if stage == "decode":
        qo_indptr_bottom = np.arange(0, batch_size + 1, dtype=np.int32)
    else:
        qo_indptr_bottom = np.arange(
            0, batch_size + 1, dtype=np.int32) * unique_kv_len
    multi_level_wrapper.plan(
        [qo_indptr_top, qo_indptr_bottom],
        [shared_kv_indptr, unique_kv_indptr],
        [shared_kv_indices, unique_kv_indices],
        [shared_last_page_len, unique_last_page_len],
        num_heads, num_heads, head_dim, page_size,
        **({"causal": causal} if stage == "append" else {}),
    )
    o_multi_level = multi_level_wrapper.run(q, kv_data)

    if stage == "decode":
        two_level = fi.BatchDecodeWithSharedPrefixPagedKVCacheWrapper(
            workspace, kv_layout)
        two_level.begin_forward(
            unique_kv_indptr, unique_kv_indices, unique_last_page_len,
            num_heads, num_heads, head_dim, page_size)
        o_two_level = two_level.forward(q, k_shared, v_shared, kv_data)
    else:
        two_level = fi.BatchPrefillWithSharedPrefixPagedKVCacheWrapper(
            workspace, kv_layout)
        two_level.begin_forward(
            q_indptr, unique_kv_indptr, unique_kv_indices,
            unique_last_page_len, num_heads, num_heads, head_dim,
            page_size)
        o_two_level = two_level.forward(
            q, k_shared, v_shared, kv_data, causal=causal)

    np.testing.assert_allclose(
        np.asarray(o_multi_level, np.float32),
        np.asarray(o_two_level, np.float32), rtol=1e-3, atol=1e-3)


@pytest.mark.parametrize("seed", [0])
@pytest.mark.parametrize("num_tries", [8])
def test_merge_state_in_place_with_mask(seed, num_tries):
    """Reference test_merge_state_in_place_with_mask
    (test_shared_prefix_kernels.py:233-312), functional form: the
    returned (v, s) play the role of the mutated buffers."""
    seq_len, num_heads, head_dim = 512, 32, 128
    keys = jax.random.split(jax.random.PRNGKey(seed), 5)
    va = jax.random.normal(keys[0], (seq_len, num_heads, head_dim),
                           jnp.float16)
    sa = jax.random.normal(keys[1], (seq_len, num_heads), jnp.float32)
    vb = jax.random.normal(keys[2], (seq_len, num_heads, head_dim),
                           jnp.float16)
    sb = jax.random.normal(keys[3], (seq_len, num_heads), jnp.float32)

    # no mask: result differs from the input state
    v_ref, s_ref = fi.merge_state_in_place(va, sa, vb, sb)
    assert not np.allclose(np.asarray(v_ref), np.asarray(va))
    assert not np.allclose(np.asarray(s_ref), np.asarray(sa))

    # all-ones mask == no mask
    ones = jnp.ones((seq_len,), bool)
    v1, s1 = fi.merge_state_in_place(va, sa, vb, sb, mask=ones)
    np.testing.assert_allclose(np.asarray(v1, np.float32),
                               np.asarray(v_ref, np.float32),
                               rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s_ref),
                               rtol=1e-3, atol=1e-3)

    # all-zeros mask: unchanged inputs
    zeros = jnp.zeros((seq_len,), bool)
    v0, s0 = fi.merge_state_in_place(va, sa, vb, sb, mask=zeros)
    np.testing.assert_allclose(np.asarray(v0, np.float32),
                               np.asarray(va, np.float32),
                               rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(np.asarray(s0), np.asarray(sa),
                               rtol=1e-3, atol=1e-3)

    # random masks: merged where True, untouched where False
    for k in jax.random.split(keys[4], num_tries):
        mask = jax.random.uniform(k, (seq_len,)) > 0.5
        vm, sm = fi.merge_state_in_place(va, sa, vb, sb, mask=mask)
        m = np.asarray(mask)
        np.testing.assert_allclose(
            np.asarray(vm, np.float32)[~m],
            np.asarray(va, np.float32)[~m], rtol=1e-3, atol=1e-3)
        np.testing.assert_allclose(
            np.asarray(sm)[~m], np.asarray(sa)[~m], rtol=1e-3, atol=1e-3)
        np.testing.assert_allclose(
            np.asarray(vm, np.float32)[m],
            np.asarray(v_ref, np.float32)[m], rtol=1e-3, atol=1e-3)
        np.testing.assert_allclose(
            np.asarray(sm)[m], np.asarray(s_ref)[m], rtol=1e-3, atol=1e-3)


def test_shared_prefix_causal_toggle_and_planned_scale():
    """Review pins: forward(causal=True) then forward(causal=False) must
    re-plan back (no stale causal mask), and a planned sm_scale must
    apply to BOTH merged halves."""
    B, U, S, H, D, PS = 2, 8, 16, 4, 64, 8
    keys = jax.random.split(jax.random.PRNGKey(1), 5)
    q = jax.random.normal(keys[0], (B * U, H, D), jnp.float16)
    q_indptr = np.arange(0, B + 1, dtype=np.int32) * U
    k_s = jax.random.normal(keys[1], (S, H, D), jnp.float16)
    v_s = jax.random.normal(keys[2], (S, H, D), jnp.float16)
    pages_u = B * ceil_div(U, PS)
    kv = jnp.zeros((ceil_div(S, PS) + pages_u, 2, PS, H, D), jnp.float16)
    s_idx = np.arange(ceil_div(S, PS), dtype=np.int32)
    s_indptr = np.arange(0, 2, dtype=np.int32) * ceil_div(S, PS)
    s_last = np.full((1,), (S - 1) % PS + 1, np.int32)
    kv = fi.append_paged_kv_cache(
        k_s, v_s,
        *fi.get_batch_indices_positions(
            np.arange(0, 2, dtype=np.int32) * S,
            fi.get_seq_lens(s_indptr, s_last, PS), S),
        kv, s_idx, s_indptr, s_last, "NHD")
    k_u = jax.random.normal(keys[3], (B * U, H, D), jnp.float16)
    v_u = jax.random.normal(keys[4], (B * U, H, D), jnp.float16)
    u_idx = np.arange(pages_u, dtype=np.int32) + ceil_div(S, PS)
    u_indptr = np.arange(0, B + 1, dtype=np.int32) * ceil_div(U, PS)
    u_last = np.full((B,), (U - 1) % PS + 1, np.int32)
    kv = fi.append_paged_kv_cache(
        k_u, v_u,
        *fi.get_batch_indices_positions(
            np.arange(0, B + 1, dtype=np.int32) * U,
            fi.get_seq_lens(u_indptr, u_last, PS), B * U),
        kv, u_idx, u_indptr, u_last, "NHD")

    w = fi.BatchPrefillWithSharedPrefixPagedKVCacheWrapper(None, "NHD")
    sm = 0.05  # deliberately non-default: must reach BOTH halves
    w.begin_forward(q_indptr, u_indptr, u_idx, u_last, H, H, D, PS,
                    sm_scale=sm)
    o_nc1 = w.forward(q, k_s, v_s, kv, causal=False)
    o_c = w.forward(q, k_s, v_s, kv, causal=True)
    o_nc2 = w.forward(q, k_s, v_s, kv, causal=False)
    # toggling back must restore the non-causal result exactly
    np.testing.assert_allclose(np.asarray(o_nc1, np.float32),
                               np.asarray(o_nc2, np.float32))
    assert not np.allclose(np.asarray(o_c, np.float32),
                           np.asarray(o_nc1, np.float32), atol=1e-3)
    # oracle with the same sm_scale on both halves
    o_s, lse_s = fi.prefill.single_prefill_with_kv_cache(
        q, k_s, v_s, causal=False, sm_scale=sm, return_lse=True)
    pw = fi.prefill.BatchPrefillWithPagedKVCacheWrapper(None, "NHD")
    pw.plan(q_indptr, u_indptr, u_idx, u_last, H, H, D, PS,
            causal=False, sm_scale=sm)
    o_u, lse_u = pw.run(q, kv, return_lse=True)
    from flashinfer_tpu.ops.merge import merge_state

    ref, _ = merge_state(o_s, lse_s, o_u, lse_u)
    np.testing.assert_allclose(np.asarray(o_nc1, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=1e-3, atol=1e-3)
    # kwargs are not silently swallowed
    with pytest.raises(TypeError, match="unsupported"):
        w.forward(q, k_s, v_s, kv, bogus_flag=True)


def test_shared_prefix_forward_scale_override_replans():
    """Round-5 high-sweep pin: a forward-time sm_scale override must
    reach BOTH merged halves (it re-plans the unique half), positional
    causal in plan() binds correctly, and forward before plan raises
    actionably."""
    B, U, S, H, D, PS = 2, 8, 16, 4, 64, 8
    keys = jax.random.split(jax.random.PRNGKey(3), 5)
    q = jax.random.normal(keys[0], (B * U, H, D), jnp.float16)
    q_indptr = np.arange(0, B + 1, dtype=np.int32) * U
    k_s = jax.random.normal(keys[1], (S, H, D), jnp.float16)
    v_s = jax.random.normal(keys[2], (S, H, D), jnp.float16)
    pages_u = B * ceil_div(U, PS)
    kv = jnp.zeros((ceil_div(S, PS) + pages_u, 2, PS, H, D), jnp.float16)
    s_idx = np.arange(ceil_div(S, PS), dtype=np.int32)
    s_indptr = np.arange(0, 2, dtype=np.int32) * ceil_div(S, PS)
    s_last = np.full((1,), (S - 1) % PS + 1, np.int32)
    kv = fi.append_paged_kv_cache(
        k_s, v_s,
        *fi.get_batch_indices_positions(
            np.arange(0, 2, dtype=np.int32) * S,
            fi.get_seq_lens(s_indptr, s_last, PS), S),
        kv, s_idx, s_indptr, s_last, "NHD")
    k_u = jax.random.normal(keys[3], (B * U, H, D), jnp.float16)
    v_u = jax.random.normal(keys[4], (B * U, H, D), jnp.float16)
    u_idx = np.arange(pages_u, dtype=np.int32) + ceil_div(S, PS)
    u_indptr = np.arange(0, B + 1, dtype=np.int32) * ceil_div(U, PS)
    u_last = np.full((B,), (U - 1) % PS + 1, np.int32)
    kv = fi.append_paged_kv_cache(
        k_u, v_u,
        *fi.get_batch_indices_positions(
            np.arange(0, B + 1, dtype=np.int32) * U,
            fi.get_seq_lens(u_indptr, u_last, PS), B * U),
        kv, u_idx, u_indptr, u_last, "NHD")

    # forward before plan: actionable error, not AttributeError
    w0 = fi.BatchPrefillWithSharedPrefixPagedKVCacheWrapper(None, "NHD")
    with pytest.raises(RuntimeError, match="begin_forward"):
        w0.forward(q, k_s, v_s, kv)

    # positional causal=True in plan binds correctly
    w = fi.BatchPrefillWithSharedPrefixPagedKVCacheWrapper(None, "NHD")
    w.plan(q_indptr, u_indptr, u_idx, u_last, H, H, D, PS, True)
    o_default = w.forward(q, k_s, v_s, kv, causal=True)
    # forward sm_scale override == planning with that scale up front
    o_override = w.forward(q, k_s, v_s, kv, causal=True, sm_scale=0.05)
    w2 = fi.BatchPrefillWithSharedPrefixPagedKVCacheWrapper(None, "NHD")
    w2.begin_forward(q_indptr, u_indptr, u_idx, u_last, H, H, D, PS,
                     sm_scale=0.05)
    o_planned = w2.forward(q, k_s, v_s, kv, causal=True)
    np.testing.assert_allclose(np.asarray(o_override, np.float32),
                               np.asarray(o_planned, np.float32),
                               rtol=1e-3, atol=1e-3)
    assert not np.allclose(np.asarray(o_override, np.float32),
                           np.asarray(o_default, np.float32), atol=1e-3)
