"""Real-TPU smoke tests: Mosaic-compile the Pallas kernels on hardware.

These auto-skip off-TPU (tpu_only marker).  They exist because interpret
mode validates semantics but NOT Mosaic compilation; run them first on any
new chip generation.  Keep shapes small — each test is one compile.
(See memory: flash-kernel compiles have wedged the shared v5e tunnel;
timeouts around this file's invocation are the caller's job.)
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import flashinfer_tpu as fi
from flashinfer_tpu.testing import attention_ref

pytestmark = pytest.mark.tpu_only


def test_paged_decode_kernel_compiles():
    from flashinfer_tpu.ops import paged_decode_attention, xla_paged_decode

    B, HQ, HKV, D, PS, P = 4, 8, 2, 128, 16, 8
    kc = jax.random.normal(jax.random.PRNGKey(0), (32, HKV, PS, D), jnp.bfloat16)
    vc = jax.random.normal(jax.random.PRNGKey(1), (32, HKV, PS, D), jnp.bfloat16)
    q = jax.random.normal(jax.random.PRNGKey(2), (B, HQ, D), jnp.bfloat16)
    pt = jax.random.randint(jax.random.PRNGKey(3), (B, P), 0, 32)
    lens = jnp.array([100, 17, 128, 1], jnp.int32)
    o = paged_decode_attention(q, kc, vc, pt, lens, sm_scale=0.0883, kv_layout="HND")
    ref = xla_paged_decode(
        q, jnp.swapaxes(kc, 1, 2), jnp.swapaxes(vc, 1, 2), pt, lens,
        sm_scale=0.0883,
    )
    np.testing.assert_allclose(
        np.asarray(o, np.float32), np.asarray(ref, np.float32), rtol=3e-2, atol=3e-2
    )


def test_flash_kernel_compiles_small():
    from flashinfer_tpu.ops import flash_attention

    T, H, KVH, D = 256, 8, 2, 128
    q = jax.random.normal(jax.random.PRNGKey(0), (T, H, D), jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(1), (T, KVH, D), jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(2), (T, KVH, D), jnp.bfloat16)
    seg = jnp.zeros((T,), jnp.int32)
    pos = jnp.arange(T)
    out = flash_attention(q, k, v, seg, seg, pos, pos, causal=True, sm_scale=0.0883)
    ref = attention_ref(q, k, v, causal=True, sm_scale=0.0883)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), rtol=3e-2, atol=3e-2
    )


def test_mla_decode_kernel_compiles():
    from flashinfer_tpu.ops.mla_decode import (
        mla_paged_decode_attention, xla_mla_paged_decode,
    )

    B, H, d_ckv, d_kpe, PS = 2, 16, 512, 64, 16
    ckv = jax.random.normal(jax.random.PRNGKey(0), (16, PS, d_ckv), jnp.bfloat16)
    kpe = jax.random.normal(jax.random.PRNGKey(1), (16, PS, d_kpe), jnp.bfloat16)
    qn = jax.random.normal(jax.random.PRNGKey(2), (B, H, d_ckv), jnp.bfloat16)
    qp = jax.random.normal(jax.random.PRNGKey(3), (B, H, d_kpe), jnp.bfloat16)
    pt = jnp.arange(8, dtype=jnp.int32).reshape(2, 4)
    lens = jnp.array([60, 33], jnp.int32)
    sm = 1 / np.sqrt(d_ckv + d_kpe)
    o = mla_paged_decode_attention(qn, qp, ckv, kpe, pt, lens, sm_scale=sm)
    ref = xla_mla_paged_decode(qn, qp, ckv, kpe, pt, lens, sm_scale=sm)
    np.testing.assert_allclose(
        np.asarray(o, np.float32), np.asarray(ref, np.float32), rtol=5e-2, atol=5e-2
    )


def test_bsr_kernel_compiles():
    w = fi.BlockSparseAttentionWrapper(backend="pallas")
    M = N = 256
    ind = np.array([0, 1, 3], np.int32)
    idx = np.array([0, 0, 1], np.int32)
    w.plan(ind, idx, M, N, 128, 128, 4, 4, 128)
    q = jax.random.normal(jax.random.PRNGKey(0), (M, 4, 128), jnp.bfloat16)
    out = w.run(q, q, q)
    assert np.isfinite(np.asarray(out, np.float32)).all()
