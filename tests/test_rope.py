"""RoPE correctness vs an eager numpy reference (mirrors the reference's
tests/test_helpers/rope_reference.py pattern)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import flashinfer_tpu as fi


def ref_rope(x, pos, rotary_dim, interleave, rope_scale, rope_theta):
    x = np.asarray(x, np.float32)
    n, h, d = x.shape
    i = np.arange(rotary_dim // 2, dtype=np.float32)
    freqs = 1.0 / (rope_scale * rope_theta ** (2 * i / rotary_dim))
    ang = pos[:, None].astype(np.float32) * freqs[None, :]
    cos, sin = np.cos(ang)[:, None, :], np.sin(ang)[:, None, :]
    out = x.copy()
    rot = x[..., :rotary_dim]
    if interleave:
        x1, x2 = rot[..., 0::2], rot[..., 1::2]
        out[..., 0:rotary_dim:2] = x1 * cos - x2 * sin
        out[..., 1:rotary_dim:2] = x2 * cos + x1 * sin
    else:
        half = rotary_dim // 2
        x1, x2 = rot[..., :half], rot[..., half:]
        out[..., :half] = x1 * cos - x2 * sin
        out[..., half:rotary_dim] = x2 * cos + x1 * sin
    return out


@pytest.mark.quick
@pytest.mark.parametrize("interleave", [False, True])
@pytest.mark.parametrize("rotary_dim", [64, 128])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_apply_rope_pos_ids(interleave, rotary_dim, dtype):
    nnz, qh, kh, d = 33, 8, 2, 128
    q = jax.random.normal(jax.random.PRNGKey(0), (nnz, qh, d), dtype)
    k = jax.random.normal(jax.random.PRNGKey(1), (nnz, kh, d), dtype)
    pos = jax.random.randint(jax.random.PRNGKey(2), (nnz,), 0, 2048)
    qo, ko = fi.apply_rope_pos_ids(
        q, k, pos, rotary_dim=rotary_dim, interleave=interleave
    )
    tol = 3e-2 if dtype == jnp.bfloat16 else 1e-4
    np.testing.assert_allclose(
        np.asarray(qo, np.float32),
        ref_rope(q, np.asarray(pos), rotary_dim, interleave, 1.0, 1e4),
        rtol=tol, atol=tol,
    )
    np.testing.assert_allclose(
        np.asarray(ko, np.float32),
        ref_rope(k, np.asarray(pos), rotary_dim, interleave, 1.0, 1e4),
        rtol=tol, atol=tol,
    )


def test_apply_rope_indptr_matches_pos_ids():
    indptr = jnp.array([0, 3, 8], jnp.int32)
    offsets = jnp.array([100, 5], jnp.int32)
    nnz = 8
    q = jax.random.normal(jax.random.PRNGKey(0), (nnz, 4, 64), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1), (nnz, 1, 64), jnp.float32)
    qo, ko = fi.apply_rope(q, k, indptr, offsets)
    pos = jnp.array([100, 101, 102, 5, 6, 7, 8, 9], jnp.int32)
    qr, kr = fi.apply_rope_pos_ids(q, k, pos)
    np.testing.assert_allclose(np.asarray(qo), np.asarray(qr), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(ko), np.asarray(kr), rtol=1e-6)


def test_cos_sin_cache_matches_direct():
    nnz, d = 16, 128
    q = jax.random.normal(jax.random.PRNGKey(0), (nnz, 4, d), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1), (nnz, 2, d), jnp.float32)
    pos = jax.random.randint(jax.random.PRNGKey(2), (nnz,), 0, 512)
    cache = fi.generate_cos_sin_cache(512, d, rope_theta=1e4)
    qo, ko = fi.apply_rope_with_cos_sin_cache(q, k, cache, pos)
    qr, kr = fi.apply_rope_pos_ids(q, k, pos)
    np.testing.assert_allclose(np.asarray(qo), np.asarray(qr), rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(np.asarray(ko), np.asarray(kr), rtol=1e-3, atol=1e-4)


def test_llama31_rope_longwave_matches_scaled_plain():
    """For very long wavelengths (low freq), llama3.1 scaling divides freqs by
    rope_scale — check limiting behavior on the lowest-frequency dims."""
    nnz, d = 4, 64
    q = jax.random.normal(jax.random.PRNGKey(0), (nnz, 1, d), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1), (nnz, 1, d), jnp.float32)
    pos = jnp.arange(nnz, dtype=jnp.int32)
    qo, _ = fi.apply_llama31_rope_pos_ids(q, k, pos)
    assert qo.shape == q.shape
    assert not np.allclose(np.asarray(qo), np.asarray(q))
