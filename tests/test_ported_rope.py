"""Migration proof #7: mechanical port of the reference test file
``/root/reference/tests/attention/test_rope.py`` (test_rope,
test_rope_pos_ids, test_rope_cos_sin_cache) — especially load-bearing
here because this package routes every fused-RoPE attention variant to
the EXPLICIT rope ops; these matrices are the proof the explicit ops
match the reference's numerics (llama + llama3.1 frequency scaling,
partial rotary, interleaved and non-interleaved layouts, neox and
gpt-j cos-sin-cache styles).

The oracle is reimplemented from the PUBLIC Llama rotation formulas in
numpy (the reference's tests/test_helpers/rope_reference.py is not
copied): complex pairwise rotation with freq_i = theta^(-2i/rd), and
the Llama-3.1 wavelength-banded frequency smoothing (factor 8, low/high
factors 1/4, original context 8192).

Deviations (written reasons):
- ``inplace=True`` rows call the *_inplace names, which here RETURN the
  rotated pair (functional arrays; the names exist for call parity —
  docs/migration.md); results must equal the non-inplace call.
- idtype int64 rows run (indices are canonicalized); matrix sampled by
  the shared 1/48 rank sampler.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import flashinfer_tpu as fi
from tests.test_ported_batch_prefill import FULL, _sample

_ROPE_ELEM_CAP = 2 ** 24  # nnz*H*D above this: f64 oracle is multi-GB


def _rope_gate(nnz, heads, head_dim):
    if not FULL and nnz * heads * head_dim > _ROPE_ELEM_CAP:
        pytest.skip(
            f"rope oracle of {nnz * heads * head_dim:.1e} elements "
            "exceeds the CPU CI cap; FLASHINFER_TPU_FULL_MATRIX run")


def _llama31_scale_freqs(freqs, factor=8.0, low=1.0, high=4.0,
                         old_ctx=8192):
    wavelen = 2 * np.pi / freqs
    low_wav = old_ctx / low
    high_wav = old_ctx / high
    smooth = (old_ctx / wavelen - low) / (high - low)
    scaled = np.where(
        wavelen > low_wav, freqs / factor,
        np.where(wavelen < high_wav, freqs,
                 (1 - smooth) * freqs / factor + smooth * freqs))
    return scaled


def _rope_oracle(x, pos, rotary_dim, theta, llama31, interleave):
    """Public Llama rotation math: pairs rotated by pos * freq_i."""
    xf = np.asarray(x, np.float64)
    nnz, H, D = xf.shape
    half = rotary_dim // 2
    freqs = theta ** (-np.arange(0, half, dtype=np.float64) * 2 / rotary_dim)
    if llama31:
        freqs = _llama31_scale_freqs(freqs)
    ang = np.asarray(pos, np.float64)[:, None] * freqs[None, :]  # [nnz, half]
    cos, sin = np.cos(ang), np.sin(ang)
    out = xf.copy()
    if interleave:
        x1 = xf[..., 0:rotary_dim:2]
        x2 = xf[..., 1:rotary_dim:2]
        out[..., 0:rotary_dim:2] = x1 * cos[:, None] - x2 * sin[:, None]
        out[..., 1:rotary_dim:2] = x1 * sin[:, None] + x2 * cos[:, None]
    else:
        x1 = xf[..., :half]
        x2 = xf[..., half:rotary_dim]
        out[..., :half] = x1 * cos[:, None] - x2 * sin[:, None]
        out[..., half:rotary_dim] = x1 * sin[:, None] + x2 * cos[:, None]
    return out


@pytest.mark.parametrize(
    "batch_size,qkv_len,num_qo_heads,num_kv_heads,offset,head_dim,"
    "llama_version,partial_rotary_factor,inplace",
    _sample("rope", [1, 19, 99, 989], [1, 4, 19, 204], [8, 16], [8],
            [0, 15, 99], [64, 128, 256], ["llama", "llama31"],
            [0.25, 0.5, 0.75, 1.0], [False, True],
            specials=[(6, "llama31"), (8, True)]),
)
def test_rope(batch_size, qkv_len, num_qo_heads, num_kv_heads, offset,
              head_dim, llama_version, partial_rotary_factor, inplace):
    """Reference test_rope (test_rope.py:24-136): indptr+offsets batch
    form, interleave=True."""
    rotary_dim = int(head_dim * partial_rotary_factor)
    nnz = batch_size * qkv_len
    _rope_gate(nnz, num_qo_heads, head_dim)
    keys = jax.random.split(jax.random.PRNGKey(0), 2)
    q = jax.random.normal(keys[0], (nnz, num_qo_heads, head_dim),
                          jnp.float16)
    k = jax.random.normal(keys[1], (nnz, num_kv_heads, head_dim),
                          jnp.float16)
    indptr = jnp.asarray(
        [i * qkv_len for i in range(batch_size + 1)], jnp.int32)
    offsets = jnp.full((batch_size,), offset, jnp.int32)
    llama31 = llama_version == "llama31"
    theta = 5e5 if llama31 else 1e4
    kwargs = dict(rotary_dim=rotary_dim, interleave=True,
                  rope_theta=theta)
    if llama31:
        fn = (fi.apply_llama31_rope_inplace if inplace
              else fi.apply_llama31_rope)
    else:
        fn = fi.apply_rope_inplace if inplace else fi.apply_rope
    q_rope, k_rope = fn(q, k, indptr, offsets, **kwargs)

    pos = np.tile(np.arange(qkv_len) + offset, batch_size)
    q_ref = _rope_oracle(q, pos, rotary_dim, theta, llama31, True)
    k_ref = _rope_oracle(k, pos, rotary_dim, theta, llama31, True)
    np.testing.assert_allclose(np.asarray(q_rope, np.float32), q_ref,
                               rtol=1e-2, atol=2e-2)
    np.testing.assert_allclose(np.asarray(k_rope, np.float32), k_ref,
                               rtol=1e-2, atol=2e-2)


@pytest.mark.parametrize(
    "batch_size,qkv_len,num_qo_heads,num_kv_heads,offset,head_dim,"
    "llama_version,partial_rotary_factor,inplace,interleave,idtype",
    _sample("rope_pos_ids", [1, 19, 99, 989], [1, 4, 19, 204], [8, 16],
            [8], [0, 15, 99], [64, 128, 256], ["llama", "llama31"],
            [0.25, 0.5, 0.75, 1.0], [False, True], [True, False],
            [jnp.int32, jnp.int64],
            specials=[(9, False), (10, jnp.int64)]),
)
def test_rope_pos_ids(batch_size, qkv_len, num_qo_heads, num_kv_heads,
                      offset, head_dim, llama_version,
                      partial_rotary_factor, inplace, interleave, idtype):
    """Reference test_rope_pos_ids (test_rope.py:139-291): pos_ids form
    must agree with the indptr+offsets form."""
    llama31 = llama_version == "llama31"
    if llama31:
        pytest.skip(
            "llama31 pos-ids rows: the llama31 frequency scaling is "
            "verified against the independent oracle in test_rope's "
            "indptr-form rows; the pos-ids spelling under test here is "
            "the generic apply_rope_pos_ids")
    rotary_dim = int(head_dim * partial_rotary_factor)
    nnz = batch_size * qkv_len
    _rope_gate(nnz, num_qo_heads, head_dim)
    keys = jax.random.split(jax.random.PRNGKey(1), 2)
    q = jax.random.normal(keys[0], (nnz, num_qo_heads, head_dim),
                          jnp.float16)
    k = jax.random.normal(keys[1], (nnz, num_kv_heads, head_dim),
                          jnp.float16)
    pos = jnp.asarray(
        np.tile(np.arange(qkv_len) + offset, batch_size), idtype)
    theta = 1e4
    rope_fn = (fi.apply_rope_pos_ids_inplace if inplace
               else fi.apply_rope_pos_ids)
    q_rope, k_rope = rope_fn(q, k, pos, rotary_dim=rotary_dim,
                             interleave=interleave, rope_theta=theta)
    q_ref = _rope_oracle(q, np.asarray(pos), rotary_dim, theta, False,
                         interleave)
    k_ref = _rope_oracle(k, np.asarray(pos), rotary_dim, theta, False,
                         interleave)
    np.testing.assert_allclose(np.asarray(q_rope, np.float32), q_ref,
                               rtol=1e-2, atol=2e-2)
    np.testing.assert_allclose(np.asarray(k_rope, np.float32), k_ref,
                               rtol=1e-2, atol=2e-2)
    if inplace:
        # the *_inplace name must agree with the non-inplace spelling
        # (functional arrays; the name exists for call parity)
        q2, k2 = fi.apply_rope_pos_ids(
            q, k, pos, rotary_dim=rotary_dim, interleave=interleave,
            rope_theta=theta)
        np.testing.assert_allclose(np.asarray(q2), np.asarray(q_rope))
        np.testing.assert_allclose(np.asarray(k2), np.asarray(k_rope))


@pytest.mark.parametrize(
    "head_size,rotary_dim,max_position_embeddings,base,is_neox_style,"
    "batch_size,seq_len,num_q_heads,num_kv_heads",
    [
        (64, 64, 32, 8000, True, 32, 32, 1, 1),
        (256, 128, 4096, 10000, True, 2, 512, 4, 2),
        (64, 32, 2048, 8432, True, 2, 199, 4, 1),
        (64, 64, 32, 8000, False, 32, 32, 1, 1),
        (256, 128, 4096, 9231, False, 3, 231, 4, 2),
        (192, 128, 4096, 9231, True, 3, 231, 3, 2),
        (80, 64, 1024, 10000, False, 4, 64, 2, 2),
        (112, 64, 2048, 12000, True, 5, 77, 2, 1),
        (160, 96, 8192, 10000, False, 2, 128, 6, 3),
    ],
)
def test_rope_cos_sin_cache(head_size, rotary_dim,
                            max_position_embeddings, base, is_neox_style,
                            batch_size, seq_len, num_q_heads,
                            num_kv_heads):
    """Reference test_rope_cos_sin_cache (test_rope.py:294-361): the
    vLLM cos-sin-cache entry in both neox (half-split) and gpt-j
    (interleaved) styles, against the public rotation formulas."""
    keys = jax.random.split(jax.random.PRNGKey(2), 2)
    nnz = batch_size * seq_len
    pos = jnp.asarray(np.tile(np.arange(seq_len), batch_size), jnp.int32)
    q = jax.random.normal(keys[0], (nnz, num_q_heads, head_size),
                          jnp.bfloat16)
    k = jax.random.normal(keys[1], (nnz, num_kv_heads, head_size),
                          jnp.bfloat16)
    cache = fi.rope.generate_cos_sin_cache(
        max_position_embeddings, rotary_dim, rope_theta=float(base))
    q_out, k_out = fi.apply_rope_with_cos_sin_cache(
        q, k, cache, pos, interleave=not is_neox_style)
    q_ref = _rope_oracle(q, np.asarray(pos), rotary_dim, float(base),
                         False, not is_neox_style)
    k_ref = _rope_oracle(k, np.asarray(pos), rotary_dim, float(base),
                         False, not is_neox_style)
    np.testing.assert_allclose(np.asarray(q_out, np.float32), q_ref,
                               rtol=2e-2, atol=4e-2)
    np.testing.assert_allclose(np.asarray(k_out, np.float32), k_ref,
                               rtol=2e-2, atol=4e-2)
