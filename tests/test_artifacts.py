"""Artifact bundles: pack/unpack round-trip, checksum enforcement, status.

Reference parity target: ``/root/reference/flashinfer/artifacts.py``
(cubin artifactory) re-designed as checksummed XLA-cache + tactics
bundles (``flashinfer_tpu/artifacts.py`` module docstring).
"""

import json
import tarfile

import pytest


@pytest.fixture()
def fake_cache(tmp_path, monkeypatch):
    from flashinfer_tpu import env

    root = tmp_path / "cache"
    (root / "xla_cache").mkdir(parents=True)
    (root / "xla_cache" / "exec_abc.bin").write_bytes(b"\x00" * 64)
    (root / "autotuner").mkdir()
    (root / "autotuner" / "tactics.json").write_text(
        json.dumps({"meta": {}, "tactics": {"k": 1}})
    )
    monkeypatch.setenv("FLASHINFER_TPU_CACHE_DIR", str(root))
    assert env.cache_dir() == root
    return root


def test_pack_unpack_round_trip(fake_cache, tmp_path):
    from flashinfer_tpu import artifacts

    bundle = artifacts.pack_artifacts(tmp_path / "b.tgz")
    assert bundle.is_file()
    # manifest covers every member incl. shipped tuning configs
    with tarfile.open(bundle) as tar:
        names = set(tar.getnames())
    assert "xla_cache/exec_abc.bin" in names
    assert "autotuner/tactics.json" in names
    assert artifacts.CheckSumHash.MANIFEST in names
    assert any(n.startswith("tuning_configs/") for n in names)

    dest = tmp_path / "restored"
    n = artifacts.unpack_artifacts(bundle, cache_dir=dest)
    assert n >= 3
    assert (dest / "xla_cache" / "exec_abc.bin").read_bytes() == b"\x00" * 64
    assert (dest / "autotuner" / "tactics.json").is_file()


def test_unpack_rejects_tampered_bundle(fake_cache, tmp_path):
    from flashinfer_tpu import artifacts

    bundle = artifacts.pack_artifacts(tmp_path / "b.tgz")
    # flip a byte inside the gzip stream -> either checksum failure or a
    # tar/gzip read error; both must refuse to seed the cache
    data = bytearray(bundle.read_bytes())
    data[len(data) // 2] ^= 0xFF
    bad = tmp_path / "bad.tgz"
    bad.write_bytes(bytes(data))
    with pytest.raises(Exception):
        artifacts.unpack_artifacts(bad, cache_dir=tmp_path / "x")


def test_unpack_rejects_manifestless_and_truncated(fake_cache, tmp_path):
    from flashinfer_tpu import artifacts

    # plain tar with no manifest -> ValueError (documented contract)
    plain = tmp_path / "plain.tgz"
    with tarfile.open(plain, "w:gz") as tar:
        tar.add(fake_cache / "autotuner" / "tactics.json",
                arcname="autotuner/tactics.json")
    with pytest.raises(ValueError, match="missing"):
        artifacts.unpack_artifacts(plain, cache_dir=tmp_path / "a")

    # manifest present but a listed member dropped -> ValueError
    bundle = artifacts.pack_artifacts(tmp_path / "b.tgz")
    filtered = tmp_path / "filtered.tgz"
    with tarfile.open(bundle) as src, tarfile.open(filtered, "w:gz") as dst:
        for m in src.getmembers():
            if m.name.startswith("xla_cache/"):
                continue  # drop the executables, keep the manifest
            dst.addfile(m, src.extractfile(m))
    with pytest.raises(ValueError, match="missing from the bundle"):
        artifacts.unpack_artifacts(filtered, cache_dir=tmp_path / "c")


def test_bundle_tuning_configs_reach_autotuner(fake_cache, tmp_path,
                                               monkeypatch):
    """A bundle-installed tuning table must be served by AutoTuner.lookup
    (the fleet-distribution path: cache-dir copy overrides package)."""
    import json as _json

    from flashinfer_tpu import artifacts, autotuner

    monkeypatch.setattr(autotuner, "_device_config_key", lambda: "fakechip")
    (fake_cache / "tuning_configs").mkdir()
    (fake_cache / "tuning_configs" / "fakechip.json").write_text(
        _json.dumps({"tactics": {
            # a registered knob reaches lookup; an unregistered one is
            # dropped by the validating loader (the L006 runtime belt)
            "rmsnorm.row_block|1_2": 7,
            "some_renamed_op.knob|1_2": 7,
        }})
    )
    t = autotuner.AutoTuner()
    assert t.lookup("rmsnorm.row_block", (1, 2)) == 7
    assert t.lookup("some_renamed_op.knob", (1, 2), default="dropped") \
        == "dropped"


def test_status_and_listing(fake_cache):
    from flashinfer_tpu import artifacts

    status = dict(artifacts.get_artifacts_status())
    assert status["xla_cache"] is True
    assert status["autotuner"] is True
    assert artifacts.get_available_cubin_files() == ("exec_abc.bin",)
    sums = artifacts.get_checksums(["autotuner"])
    assert list(sums) == ["autotuner/tactics.json"]
    subs = {s for s, _ in artifacts.get_subdir_file_list()}
    assert {"xla_cache", "autotuner"} <= subs


def test_clear_artifacts(fake_cache):
    from flashinfer_tpu import artifacts

    artifacts.clear_cubin(cache_dir=fake_cache)
    assert not (fake_cache / "xla_cache").exists()
    assert not (fake_cache / "autotuner").exists()
    # shipped tuning configs untouched
    assert artifacts.get_available_header_files()


def test_temp_env_var(monkeypatch):
    import os

    from flashinfer_tpu import artifacts

    monkeypatch.delenv("FI_TPU_TEST_VAR", raising=False)
    with artifacts.temp_env_var("FI_TPU_TEST_VAR", "1"):
        assert os.environ["FI_TPU_TEST_VAR"] == "1"
    assert "FI_TPU_TEST_VAR" not in os.environ
