"""Wedge-resilience tests for the bench.py orchestrator.

Round-2 postmortem (VERDICT.md): a wedged chip turned the round's
deliverable into rc=124 with no JSON line.  These tests pin the contract
the orchestrator must keep — a hung phase still yields its landed rows,
and a sick chip still yields one parseable JSON line with
``"wedged": true`` — without touching any TPU (the hung phase is a stub).
"""

import importlib.util
import json
import os
import sys

import pytest

_BENCH = os.path.join(os.path.dirname(__file__), os.pardir, "bench.py")


@pytest.fixture()
def bench_mod():
    spec = importlib.util.spec_from_file_location("bench_under_test", _BENCH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_phase_rows_survive_timeout(bench_mod, monkeypatch):
    monkeypatch.setenv("BENCH_SELFTEST_HANG", "1")
    # window must cover phase-subprocess startup (apply_platform_from_env
    # imports jax, ~2-5s) before the rows land and the hang begins
    rows, ok, detail = bench_mod._run_phase("selftest", False, timeout_s=20)
    assert not ok
    assert "timed out" in detail
    assert [r["n"] for r in rows] == [1, 2]


def test_phase_rows_complete(bench_mod, monkeypatch):
    monkeypatch.delenv("BENCH_SELFTEST_HANG", raising=False)
    rows, ok, detail = bench_mod._run_phase("selftest", False, timeout_s=30)
    assert ok and len(rows) == 2


def test_orchestrate_wedged_chip_emits_json(bench_mod, monkeypatch, capsys,
                                            tmp_path):
    from flashinfer_tpu import compile_guard

    monkeypatch.setattr(
        compile_guard, "probe",
        lambda timeout_s=0: {"healthy": False, "elapsed": 0.0,
                             "detail": "stub wedge"},
    )
    rc = bench_mod.orchestrate(sweep=False, bank=False)
    assert rc == 0
    line = capsys.readouterr().out.strip().splitlines()[-1]
    result = json.loads(line)
    assert result["wedged"] is True
    assert result["value"] == 0.0
    assert result["metric"] == "batch_decode_attention_bandwidth_bs64_ctx4k"


def test_orchestrate_hung_phase_partial_json(bench_mod, monkeypatch, capsys):
    from flashinfer_tpu import compile_guard

    monkeypatch.setattr(
        compile_guard, "probe",
        lambda timeout_s=0: {"healthy": True, "elapsed": 1.0, "detail": "ok"},
    )
    monkeypatch.setenv("BENCH_SELFTEST_HANG", "1")
    monkeypatch.setitem(bench_mod.PHASE_TIMEOUT_S, "selftest", 5)
    rc = bench_mod.orchestrate(sweep=False, bank=False, phases=["selftest"])
    assert rc == 0
    line = capsys.readouterr().out.strip().splitlines()[-1]
    result = json.loads(line)
    assert result["wedged"] is True  # phase timed out -> flagged, not rc=124


def test_bank_appends_record(bench_mod, tmp_path, monkeypatch):
    # _bank writes next to bench.py; point it at a temp copy instead
    import shutil

    tmp_bench = tmp_path / "bench.py"
    shutil.copy(_BENCH, tmp_bench)
    spec = importlib.util.spec_from_file_location("bench_tmp", str(tmp_bench))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod._bank({"result": {"value": 1.0}, "rows": []})
    banked = (tmp_path / "BENCH_BANKED.md").read_text()
    assert "bench.py run" in banked and '"value": 1.0' in banked
