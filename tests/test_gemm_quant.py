"""GEMM + quantization + topk + logits-pipeline tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import flashinfer_tpu as fi
from flashinfer_tpu.logits_processor import (
    LogitsPipe, MinP, Sample, Softmax, Temperature, TopK, TopP,
)


def test_mm_bf16():
    a = jax.random.normal(jax.random.PRNGKey(0), (64, 128))
    b = jax.random.normal(jax.random.PRNGKey(1), (128, 32))
    out = fi.mm_bf16(a, b, out_dtype=jnp.float32)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(a) @ np.asarray(b), rtol=2e-2, atol=2e-1
    )


def test_fp8_roundtrip_and_bmm():
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 32, 64))
    q8, scale = fi.quantize_fp8_per_tensor(x)
    assert q8.dtype == jnp.float8_e4m3fn
    back = fi.dequantize_fp8(q8, scale, out_dtype=jnp.float32)
    err = np.abs(np.asarray(back) - np.asarray(x))
    # e4m3 quantization: mean error tiny, tail bounded by the coarse spacing
    # near amax (spacing ~ amax/14 at the top bin)
    assert err.mean() < 0.02, err.mean()
    assert err.max() < float(np.abs(np.asarray(x)).max()) / 7.0, err.max()

    y = jax.random.normal(jax.random.PRNGKey(1), (4, 64, 16))
    qy, sy = fi.quantize_fp8_per_tensor(y)
    out = fi.bmm_fp8(q8, qy, scale, sy, out_dtype=jnp.float32)
    # compare against the matmul of the dequantized operands (isolates the
    # matmul path from quantization error)
    ref = np.einsum(
        "bmk,bkn->bmn",
        np.asarray(fi.dequantize_fp8(q8, scale, out_dtype=jnp.float32)),
        np.asarray(fi.dequantize_fp8(qy, sy, out_dtype=jnp.float32)),
    )
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-2, atol=0.2)


@pytest.mark.quick
def test_int8_mm():
    x = jax.random.normal(jax.random.PRNGKey(0), (32, 64))
    w = jax.random.normal(jax.random.PRNGKey(1), (64, 16))
    qx, sx = fi.quantize_int8(x, axis=-1)  # per-row scales [32,1]
    qw, sw = fi.quantize_int8(w, axis=0)  # per-col scales [1,16]
    out = fi.mm_int8(qx, qw, sx, sw, out_dtype=jnp.float32)
    ref = np.asarray(x) @ np.asarray(w)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=0.1, atol=0.2)


def test_grouped_gemm_and_segment_wrapper():
    k, n = 32, 16
    sizes = np.array([5, 0, 11], np.int32)
    total = sizes.sum()
    x = jax.random.normal(jax.random.PRNGKey(0), (total, k))
    ws = jax.random.normal(jax.random.PRNGKey(1), (3, k, n))
    out = fi.grouped_gemm(x, ws, jnp.asarray(sizes))
    xs = np.asarray(x)
    wn = np.asarray(ws)
    ref = np.concatenate([
        xs[0:5] @ wn[0], xs[5:5] @ wn[1], xs[5:16] @ wn[2]
    ])
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-2, atol=2e-2)

    w = fi.SegmentGEMMWrapper()
    out2 = w.run(x, ws, batch_size=3, seg_lens=jnp.asarray(sizes))
    np.testing.assert_allclose(np.asarray(out2), ref, rtol=2e-2, atol=2e-2)
    # weight_indices indirection
    out3 = w.run(x, ws, batch_size=3, seg_lens=jnp.asarray(sizes),
                 weight_indices=jnp.array([2, 2, 2]))
    ref3 = xs @ wn[2]
    np.testing.assert_allclose(np.asarray(out3), ref3, rtol=2e-2, atol=2e-2)


def test_packbits():
    bits = jnp.array([1, 0, 1, 1, 0, 0, 1, 0, 1, 1], jnp.uint8)
    out = fi.packbits(bits)
    np.testing.assert_array_equal(np.asarray(out), np.packbits(np.asarray(bits)))
    packed, indptr = fi.segment_packbits(bits, jnp.array([0, 3, 10]))
    assert np.asarray(indptr).tolist() == [0, 1, 2]
    np.testing.assert_array_equal(
        np.asarray(packed),
        np.concatenate([np.packbits(np.asarray(bits[:3])),
                        np.packbits(np.asarray(bits[3:]))]),
    )


def test_topk_page_transform():
    B, max_kv, P, PS, k = 2, 32, 4, 8, 4
    scores = jax.random.normal(jax.random.PRNGKey(0), (B, max_kv))
    table = jnp.array([[3, 1, 2, 0], [7, 6, 5, 4]], jnp.int32)
    kv_lens = jnp.array([20, 32], jnp.int32)
    rows, valid = fi.top_k_page_table_transform(scores, table, kv_lens, k, PS)
    s = np.asarray(scores).copy()
    s[0, 20:] = -np.inf
    for b in range(B):
        top_tok = np.argsort(-s[b])[:k]
        expect = set(
            int(table[b, t // PS]) * PS + t % PS for t in top_tok
        )
        assert set(np.asarray(rows[b]).tolist()) == expect
    assert bool(valid.all())


def test_logits_pipe_valid_chain():
    pipe = LogitsPipe([Temperature(), Softmax(), TopK(), TopP(), Sample()])
    logits = jax.random.normal(jax.random.PRNGKey(0), (4, 128))
    toks = pipe(logits, key=jax.random.PRNGKey(1), temperature=0.7, top_k=20,
                top_p=0.9)
    assert toks.shape == (4,) and toks.dtype == jnp.int32
    # sampled tokens must be within the joint top-k set
    p = np.asarray(jax.nn.softmax(np.asarray(logits) / 0.7, axis=-1))
    for b in range(4):
        assert p[b, int(toks[b])] >= np.sort(p[b])[::-1][19] - 1e-6


def test_logits_pipe_validation_errors():
    with pytest.raises(ValueError, match="requires probs"):
        LogitsPipe([TopP(), Sample()])
    with pytest.raises(ValueError, match="after Sample"):
        LogitsPipe([Softmax(), Sample(), TopK()])
    pipe = LogitsPipe([Softmax(), Sample()])
    with pytest.raises(ValueError, match="unknown params"):
        pipe(jnp.zeros((1, 8)), key=jax.random.PRNGKey(0), top_k=5)


def test_logits_pipe_topk_on_logits_matches_probs_domain():
    """TopK legalizes to mask-logits pre-softmax and renorm post-softmax —
    both must give the same distribution."""
    logits = jax.random.normal(jax.random.PRNGKey(0), (2, 64))
    p1 = LogitsPipe([TopK(), Softmax()])
    p2 = LogitsPipe([Softmax(), TopK()])
    d1 = p1(logits, top_k=8)
    d2 = p2(logits, top_k=8)
    np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), rtol=1e-4, atol=1e-5)


def test_mm_fp8_groupwise():
    rng = np.random.default_rng(0)
    m, k, n, bk, bn = 16, 64, 32, 16, 16
    a32 = rng.normal(size=(m, k)).astype(np.float32)
    b32 = rng.normal(size=(k, n)).astype(np.float32)
    # per-group quant
    a_g = a32.reshape(m, k // bk, bk)
    a_scale = np.abs(a_g).max(-1) / 448.0 + 1e-12
    a8 = jnp.asarray((a_g / a_scale[..., None]).reshape(m, k)).astype(jnp.float8_e4m3fn)
    b_g = b32.reshape(k // bk, bk, n // bn, bn)
    b_scale = np.abs(b_g).max(axis=(1, 3)) / 448.0 + 1e-12
    b8 = jnp.asarray((b_g / b_scale[:, None, :, None]).reshape(k, n)).astype(jnp.float8_e4m3fn)
    out = fi.mm_fp8_groupwise(a8, b8, jnp.asarray(a_scale), jnp.asarray(b_scale),
                              out_dtype=jnp.float32)
    ref = a32 @ b32
    np.testing.assert_allclose(np.asarray(out), ref, rtol=0.15, atol=0.5)


def test_quantizing_norms():
    x = jax.random.normal(jax.random.PRNGKey(0), (8, 64))
    w = jnp.ones((64,))
    q, s = fi.rmsnorm_quant_fp8(x, w)
    back = np.asarray(q, np.float32) * float(s)
    ref = np.asarray(fi.rmsnorm(x, w, backend="xla"))
    np.testing.assert_allclose(back, ref, rtol=0.1, atol=0.05)
    r = jax.random.normal(jax.random.PRNGKey(1), (8, 64))
    q2, s2, new_r = fi.fused_add_rmsnorm_quant_fp8(x, r, w)
    ref_n, ref_r = fi.fused_add_rmsnorm(x, r, w, backend="xla")
    np.testing.assert_allclose(np.asarray(new_r), np.asarray(ref_r), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(q2, np.float32) * float(s2), np.asarray(ref_n), rtol=0.1, atol=0.05
    )


# ---- grouped-quantized GEMM variants -------------------------------------


def _ragged_ref(x, w, sizes):
    out = []
    off = 0
    for g, s in enumerate(sizes):
        out.append(np.asarray(x[off:off + s], np.float32) @ np.asarray(w[g], np.float32))
        off += s
    return np.concatenate(out) if out else np.zeros((0, w.shape[-1]))


def test_group_gemm_int8():
    import flashinfer_tpu as fi
    from flashinfer_tpu.quantization import quantize_int8

    rng = np.random.default_rng(0)
    G, k, n = 3, 64, 48
    sizes = [5, 0, 9]
    x = jnp.asarray(rng.standard_normal((sum(sizes), k)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((G, k, n)), jnp.float32)
    wq, ws = quantize_int8(w, axis=1)  # per-(group, out-channel)
    out = fi.group_gemm_int8(
        x, wq, ws.reshape(G, n), jnp.asarray(sizes, jnp.int32),
        out_dtype=jnp.float32,
    )
    ref = _ragged_ref(x, np.asarray(wq, np.float32) * np.asarray(ws), sizes)
    # int8 activation quantization dominates the error budget
    rel = np.abs(np.asarray(out) - ref) / (np.abs(ref).max() + 1e-6)
    assert rel.max() < 2e-2, rel.max()


def test_group_gemm_fp8_nt_groupwise():
    import flashinfer_tpu as fi

    rng = np.random.default_rng(1)
    G, k, n = 2, 64, 64
    blk = 32
    sizes = [4, 7]
    a = jnp.asarray(rng.standard_normal((sum(sizes), k)), jnp.float8_e4m3fn)
    b = jnp.asarray(rng.standard_normal((G, n, k)), jnp.float8_e4m3fn)
    a_scale = jnp.asarray(rng.random((sum(sizes), k // blk)) + 0.5, jnp.float32)
    b_scale = jnp.asarray(rng.random((G, k // blk, n // blk)) + 0.5, jnp.float32)
    out = fi.group_gemm_fp8_nt_groupwise(
        a, b, a_scale, b_scale, jnp.asarray(sizes, jnp.int32),
        out_dtype=jnp.float32,
    )
    # reference: dequantize then ragged matmul
    af = np.asarray(a, np.float32).reshape(-1, k // blk, blk)
    af = (af * np.asarray(a_scale)[:, :, None]).reshape(-1, k)
    bf = np.asarray(b, np.float32).reshape(G, n // blk, blk, k // blk, blk)
    bf = bf * np.swapaxes(np.asarray(b_scale), 1, 2)[:, :, None, :, None]
    bw = np.swapaxes(bf.reshape(G, n, k), 1, 2)
    ref = _ragged_ref(jnp.asarray(af), jnp.asarray(bw), sizes)
    # kernel computes in bf16 after dequant (no native fp8 MXU on v5):
    # ~0.4% per-operand rounding accumulates over k=64 products
    np.testing.assert_allclose(np.asarray(out), ref, rtol=3e-2, atol=0.3)


def test_group_gemm_fp4():
    import flashinfer_tpu as fi
    from flashinfer_tpu.quantization import quantize_fp4, dequantize_fp4

    rng = np.random.default_rng(2)
    G, k, n = 2, 64, 32
    sizes = [6, 3]
    x = jnp.asarray(rng.standard_normal((sum(sizes), k)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((G, n, k)), jnp.float32)  # pack on k
    wp, ws = quantize_fp4(w)  # [G, n, k//2], [G, n, k//16]
    wp_t = jnp.swapaxes(wp, 1, 2)  # [G, k//2, n]
    ws_t = jnp.swapaxes(ws, 1, 2)  # [G, k//16, n]
    out = fi.group_gemm_fp4(
        x, wp_t, ws_t, jnp.asarray(sizes, jnp.int32), out_dtype=jnp.float32
    )
    wd = np.asarray(dequantize_fp4(wp, ws, out_dtype=jnp.float32))  # [G, n, k]
    ref = _ragged_ref(x, np.swapaxes(wd, 1, 2), sizes)
    # x and dequantized w round to bf16 inside the kernel
    np.testing.assert_allclose(np.asarray(out), ref, rtol=3e-2, atol=0.3)
