"""Wrapper contract tests: bounded recompiles (bucketing) + dtype/shape
validation — the plan/run lifecycle properties serving engines rely on."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import flashinfer_tpu as fi
from flashinfer_tpu.ops import xla_paged_decode


def _plan_run(w, kv_lens, HQ=4, HKV=2, D=64, PS=8, q_dtype=jnp.float32):
    pages_per = [-(-l // PS) for l in kv_lens]
    indptr = np.concatenate([[0], np.cumsum(pages_per)]).astype(np.int32)
    indices = np.arange(indptr[-1], dtype=np.int32)
    last = np.array(
        [l - (p - 1) * PS for l, p in zip(kv_lens, pages_per)], np.int32
    )
    w.plan(indptr, indices, last, HQ, HKV, D, PS)
    # fixed-size page pool, as in real serving (cache shape must not vary)
    npages = 64
    kc = jax.random.normal(jax.random.PRNGKey(0), (npages, PS, HKV, D), q_dtype)
    vc = jax.random.normal(jax.random.PRNGKey(1), (npages, PS, HKV, D), q_dtype)
    q = jax.random.normal(jax.random.PRNGKey(2), (len(kv_lens), HQ, D), q_dtype)
    return w.run(q, (kc, vc))


def test_bucketing_bounds_recompiles():
    """Geometries inside the same power-of-two bucket reuse one executable."""
    w = fi.BatchDecodeWithPagedKVCacheWrapper(backend="xla")
    base = xla_paged_decode._cache_size()
    _plan_run(w, [10, 20, 30])       # batch 3 -> bucket 8, pages -> bucket 4
    after_first = xla_paged_decode._cache_size()
    _plan_run(w, [31, 7, 12, 25, 9])  # batch 5 -> same batch bucket 8
    _plan_run(w, [5, 5, 5, 5, 5, 5])  # batch 6 -> same bucket
    after_same_bucket = xla_paged_decode._cache_size()
    assert after_first > base
    assert after_same_bucket == after_first, "same bucket must not recompile"
    _plan_run(w, [10] * 12)           # batch 12 -> bucket 16: one new compile
    assert xla_paged_decode._cache_size() == after_first + 1


def test_run_validates_dtype_when_planned():
    w = fi.BatchDecodeWithPagedKVCacheWrapper(backend="xla")
    PS, HQ, HKV, D = 8, 4, 2, 64
    indptr = np.array([0, 1], np.int32)
    w.plan(indptr, np.array([0], np.int32), np.array([4], np.int32),
           HQ, HKV, D, PS, q_data_type=jnp.bfloat16)
    kc = jnp.zeros((1, PS, HKV, D), jnp.bfloat16)
    q32 = jnp.zeros((1, HQ, D), jnp.float32)
    with pytest.raises(ValueError, match="q_data_type"):
        w.run(q32, (kc, kc))
    # matching dtype passes
    out = w.run(q32.astype(jnp.bfloat16), (kc, kc))
    assert out.shape == (1, HQ, D)


def test_run_validates_head_shape():
    w = fi.BatchDecodeWithPagedKVCacheWrapper(backend="xla")
    PS, HQ, HKV, D = 8, 4, 2, 64
    w.plan(np.array([0, 1], np.int32), np.array([0], np.int32),
           np.array([4], np.int32), HQ, HKV, D, PS)
    kc = jnp.zeros((1, PS, HKV, D), jnp.float32)
    with pytest.raises(ValueError, match="planned heads"):
        w.run(jnp.zeros((1, 8, D), jnp.float32), (kc, kc))
