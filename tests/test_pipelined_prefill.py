"""Interpret-mode parity suite for the pipelined work-unit prefill kernel.

ISSUE 3 tentpole proof, CPU-provable: the restructured
``ops/paged_prefill.py`` mainloop (double-buffered q/KV streaming,
plan-time block codes, unit pruning, tile packing) must match the
gather+flash oracle across the block-shape grid x {unmasked,
packed-mask, ragged} — including the packed-custom-mask variant whose
only on-chip run failed (the uint8-cast bug class at the in-kernel
bitmap expansion), so that path is exercised end-to-end off-chip.

Invariants pinned beyond oracle parity:

- **Packing is bit-exact.**  Rows outside a packed unit's span are
  identity steps of the online softmax (``p=0, alpha=1``), so packed
  and unpacked plans must produce BIT-IDENTICAL outputs.
- **Pruning is bit-exact.**  A pruned unit contributed nothing, so
  pruned and unpruned plans must also match bitwise.
- **CODE_FULL is bit-exact.**  ``where(all_true, s, -inf) == s``, so
  forcing every FULL unit back to PARTIAL must not change a single bit
  — the fast path is a pure specialization, never a numeric variant.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from flashinfer_tpu.ops.paged_prefill import (
    CODE_FULL,
    CODE_PARTIAL,
    build_prefill_work_units,
    fused_paged_prefill,
)

HQ, HKV, D, PS = 4, 2, 32, 8

# the swept block-shape grid: (block_q, pages_per_chunk) — small enough
# for interpret mode, shaped to cover partial tiles, multi-chunk kv, and
# the single-chunk degenerate
BLOCK_GRID = [(32, 2), (64, 4), (128, 2)]

# ragged geometries: uniform chunked, mixed ragged with a zero-kv and a
# zero-qo request, and single long request (the causal-pruning shape)
GEOMETRIES = {
    "uniform": ([64, 64, 64], [128, 128, 128]),
    "ragged": ([40, 7, 130, 0, 65], [64, 24, 200, 16, 0]),
    "single_long": ([192], [256]),
}


def _setup(qo_lens, kv_lens, seed=0):
    rng = np.random.default_rng(seed)
    qo_indptr = np.concatenate([[0], np.cumsum(qo_lens)]).astype(np.int32)
    pages_per = [int(np.ceil(l / PS)) for l in kv_lens]
    kv_page_indptr = np.concatenate([[0], np.cumsum(pages_per)]).astype(
        np.int32)
    npages = max(int(kv_page_indptr[-1]), 1)
    kv_page_indices = rng.permutation(npages).astype(np.int32)
    total_q = int(qo_indptr[-1])
    q = jax.random.normal(jax.random.PRNGKey(seed), (total_q, HQ, D),
                          jnp.float32)
    kc = jax.random.normal(jax.random.PRNGKey(seed + 1),
                           (npages, HKV, PS, D), jnp.float32)
    vc = jax.random.normal(jax.random.PRNGKey(seed + 2),
                           (npages, HKV, PS, D), jnp.float32)
    return qo_indptr, kv_page_indptr, kv_page_indices, q, kc, vc


def _run(qo_indptr, kv_page_indptr, kv_page_indices, kv_lens, q, kc, vc,
         bq, ppc, *, causal=True, window_left=-1, mask_flat=None,
         mask_total_bits=None, pack_tiles=True, prune=True,
         force_partial=False):
    plan_np = build_prefill_work_units(
        qo_indptr, kv_page_indptr, kv_page_indices,
        np.asarray(kv_lens, np.int64), block_q=bq, pages_per_chunk=ppc,
        page_size=PS, mask_flat=mask_flat, mask_total_bits=mask_total_bits,
        causal=causal, window_left=window_left, pack_tiles=pack_tiles,
        prune=prune,
    )
    statics = dict(num_units=plan_np.pop("num_units"),
                   block_q=plan_np.pop("block_q"),
                   pages_per_chunk=plan_np.pop("pages_per_chunk"))
    stats = plan_np.pop("stats")
    if force_partial:
        plan_np["code"] = np.where(
            plan_np["code"] == CODE_FULL, CODE_PARTIAL, plan_np["code"]
        ).astype(np.int32)
    plan = {k: jnp.asarray(v) for k, v in plan_np.items()}
    out = fused_paged_prefill(
        q, kc, vc, plan, sm_scale=D ** -0.5, causal=causal,
        window_left=window_left, **statics,
    )
    return np.asarray(out, np.float32), stats, plan_np


def _oracle(qo_indptr, kv_page_indptr, kv_page_indices, kv_lens, q, kc, vc,
            *, causal=True, window_left=-1, mask_flat=None):
    """Dense per-request attention with bottom-right (append) alignment —
    the gather+flash semantics the wrapper's fallback path implements."""
    qo_lens = qo_indptr[1:] - qo_indptr[:-1]
    total_q = int(qo_indptr[-1])
    ref = np.zeros((total_q, HQ, D), np.float32)
    off = 0
    for r in range(len(qo_lens)):
        qs, qe = int(qo_indptr[r]), int(qo_indptr[r + 1])
        n_bits = int(qo_lens[r]) * int(kv_lens[r])
        m = (np.asarray(mask_flat[off:off + n_bits]).reshape(
            int(qo_lens[r]), int(kv_lens[r])) if mask_flat is not None
            and n_bits else None)
        off += n_bits
        if qe <= qs or kv_lens[r] == 0:
            continue
        pages = kv_page_indices[kv_page_indptr[r]:kv_page_indptr[r + 1]]
        kr = np.asarray(kc)[pages].transpose(0, 2, 1, 3).reshape(
            -1, HKV, D)[: kv_lens[r]]
        vr = np.asarray(vc)[pages].transpose(0, 2, 1, 3).reshape(
            -1, HKV, D)[: kv_lens[r]]
        qr = np.asarray(q)[qs:qe]
        qpos = kv_lens[r] - qo_lens[r] + np.arange(qo_lens[r])
        kpos = np.arange(kv_lens[r])
        kg = np.repeat(kr, HQ // HKV, axis=1)
        vg = np.repeat(vr, HQ // HKV, axis=1)
        s = np.einsum("qhd,khd->hqk", qr, kg) * (D ** -0.5)
        valid = np.ones((qo_lens[r], kv_lens[r]), bool)
        if m is not None:
            valid &= m
        elif causal:
            valid &= kpos[None, :] <= qpos[:, None]
        if window_left >= 0:
            valid &= kpos[None, :] >= qpos[:, None] - window_left
        s = np.where(valid[None], s, -np.inf)
        mx = s.max(-1, keepdims=True)
        p = np.where(valid[None], np.exp(s - np.where(
            np.isfinite(mx), mx, 0.0)), 0.0)
        l = p.sum(-1, keepdims=True)
        ref[qs:qe] = np.einsum(
            "hqk,khd->qhd", np.where(l > 0, p / np.where(l > 0, l, 1.0), 0),
            vg)
    return ref


@pytest.mark.quick
@pytest.mark.parametrize("bq,ppc", BLOCK_GRID)
@pytest.mark.parametrize("geom", sorted(GEOMETRIES))
def test_unmasked_parity_and_packing_bitwise(bq, ppc, geom):
    """Unmasked causal cell of the suite: oracle parity at every swept
    block shape, plus the packing/pruning bitwise invariants."""
    qo_lens, kv_lens = GEOMETRIES[geom]
    args = _setup(qo_lens, kv_lens)
    out, stats, _ = _run(*args[:3], kv_lens, *args[3:], bq, ppc)
    ref = _oracle(args[0], args[1], args[2], kv_lens, *args[3:])
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)
    # packing and pruning are identity transforms, bit for bit
    out_unpacked, stats_u, _ = _run(
        *args[:3], kv_lens, *args[3:], bq, ppc, pack_tiles=False)
    np.testing.assert_array_equal(out, out_unpacked)
    out_unpruned, _, _ = _run(
        *args[:3], kv_lens, *args[3:], bq, ppc, prune=False)
    np.testing.assert_array_equal(out, out_unpruned)
    # the single long causal request must actually prune above-diagonal
    # chunks and pack at least as tight as the unpacked plan
    if geom == "single_long":
        assert stats["units_pruned"] > 0
    assert stats["units"] <= stats_u["units"]


@pytest.mark.parametrize("bq,ppc", BLOCK_GRID)
def test_full_code_fast_path_is_bitwise_pure(bq, ppc):
    """CODE_FULL is a specialization, not an approximation: demoting
    every FULL unit to PARTIAL must reproduce the output bit for bit."""
    qo_lens, kv_lens = GEOMETRIES["uniform"]
    args = _setup(qo_lens, kv_lens, seed=5)
    out, _, plan_np = _run(*args[:3], kv_lens, *args[3:], bq, ppc)
    if bq <= min(qo_lens):
        # tiles fit inside requests -> interior below-diagonal units must
        # classify FULL (bq > qo_len can never fill a tile's rows)
        assert (plan_np["code"] == CODE_FULL).any(), (
            f"uniform chunked geometry should classify interior units "
            f"FULL (codes={plan_np['code']})")
    out_partial, _, _ = _run(*args[:3], kv_lens, *args[3:], bq, ppc,
                             force_partial=True)
    np.testing.assert_array_equal(out, out_partial)


@pytest.mark.parametrize("bq,ppc", BLOCK_GRID)
@pytest.mark.parametrize("geom", ["uniform", "ragged"])
@pytest.mark.parametrize("use_native", [True, False])
def test_packed_mask_parity(bq, ppc, geom, use_native):
    """Packed-custom-mask cell: the EXACT in-kernel path that failed on
    chip (uint8 bitmap -> int32 widen -> f32 selector-dot expansion,
    ops/paged_prefill.py mask_bits) runs in interpret mode against the
    dense masked oracle, from LSB-first packed bytes end-to-end, with
    the C++ and numpy mask planners both covered."""
    from flashinfer_tpu import native

    if use_native and native.get_lib() is None:
        pytest.skip("native planner unavailable")
    qo_lens, kv_lens = GEOMETRIES[geom]
    args = _setup(qo_lens, kv_lens, seed=7)
    rng = np.random.default_rng(11)
    total_bits = int(np.sum(np.asarray(qo_lens) * np.asarray(kv_lens)))
    mask_bool = rng.random(total_bits) < 0.5
    packed_bytes = np.packbits(mask_bool, bitorder="little")

    lib_save = native._LIB
    if not use_native:
        native._LIB = None
    try:
        out, _, plan_np = _run(
            *args[:3], kv_lens, *args[3:], bq, ppc, causal=False,
            mask_flat=packed_bytes, mask_total_bits=total_bits)
    finally:
        native._LIB = lib_save
    ref = _oracle(args[0], args[1], args[2], kv_lens, *args[3:],
                  causal=False, mask_flat=mask_bool)
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)
    # the kernel consumed a genuine uint8 bitmap (the failing dtype)
    assert plan_np["mask_bytes"].dtype == np.uint8


def test_masked_kernel_never_casts_uint8_to_float_directly():
    """Regression pin for the on-chip failure class itself: Mosaic has
    no uint8->float cast ('Unsupported cast', banked 2026-07-31), so the
    bitmap expansion must widen through int32 first.  The parity tests
    above prove the path's NUMERICS off-chip; this pins the lowering
    shape so the compile-time failure cannot silently return."""
    import ast
    import inspect

    from flashinfer_tpu.ops import paged_prefill

    src = inspect.getsource(paged_prefill)
    tree = ast.parse(src)
    hits = []
    for node in ast.walk(tree):
        # any <expr>.astype(jnp.float32) where <expr> mentions mask_ref
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "astype"
                and "mask_ref" in ast.dump(node.func.value)):
            hits.append(ast.unparse(node))
    assert hits, "mask bitmap expansion disappeared — update this pin"
    for call in hits:
        assert "int32" in call and "float32" not in call.split(")")[0], (
            f"mask bytes must widen uint8 -> int32 before any float "
            f"cast (Mosaic 'Unsupported cast' wedge class): {call}")


@pytest.mark.parametrize("bq,ppc", [(64, 2)])
def test_window_left_parity_and_window_pruning(bq, ppc):
    qo_lens, kv_lens = GEOMETRIES["single_long"]
    args = _setup(qo_lens, kv_lens, seed=9)
    out, stats, _ = _run(*args[:3], kv_lens, *args[3:], bq, ppc,
                         window_left=48)
    ref = _oracle(args[0], args[1], args[2], kv_lens, *args[3:],
                  window_left=48)
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)
    # chunks entirely below the window are plan-pruned: strictly more
    # pruning than the causal-only plan
    _, stats_causal, _ = _run(*args[:3], kv_lens, *args[3:], bq, ppc)
    assert stats["units_pruned"] > stats_causal["units_pruned"]


def test_wrapper_fused_backend_masked_matches_gather_path():
    """Wrapper-level end-to-end: BatchPrefillWithPagedKVCacheWrapper on
    the explicit fused backend with a packed custom mask vs the gather
    (xla) fallback — the masked-prefill surface PARITY.md restated to
    'fix committed, on-chip re-proof pending', provable here off-chip."""
    import flashinfer_tpu as fi

    qo_lens, kv_lens = [24, 40], [48, 64]
    (qo_indptr, kv_page_indptr, kv_page_indices, q, kc, vc) = _setup(
        qo_lens, kv_lens, seed=13)
    q = q.astype(jnp.bfloat16)
    kc = kc.astype(jnp.bfloat16)
    vc = vc.astype(jnp.bfloat16)
    # HND cache layout for the fused path
    kc_hnd, vc_hnd = kc, vc
    last_page = (np.asarray(kv_lens)
                 - (np.asarray([np.ceil(l / PS) for l in kv_lens],
                               np.int32) - 1) * PS).astype(np.int32)
    rng = np.random.default_rng(17)
    total_bits = int(np.sum(np.asarray(qo_lens) * np.asarray(kv_lens)))
    packed_mask = np.packbits(rng.random(total_bits) < 0.6,
                              bitorder="little")

    outs = {}
    for backend in ("pallas_fused", "xla"):
        w = fi.BatchPrefillWithPagedKVCacheWrapper(
            kv_layout="HND", backend=backend)
        w.plan(
            qo_indptr, kv_page_indptr, kv_page_indices, last_page,
            HQ, HKV, D, PS, causal=True, packed_custom_mask=packed_mask,
        )
        if backend == "pallas_fused":
            cfg = w.fused_prefill_config
            assert cfg is not None and cfg["block_q"] > 0
        outs[backend] = np.asarray(
            w.run(q, (kc_hnd, vc_hnd)), np.float32)
    np.testing.assert_allclose(outs["pallas_fused"], outs["xla"],
                               rtol=3e-2, atol=3e-2)


def test_wrapper_live_retune_refreshes_plan_and_stats(monkeypatch):
    """In-run autotune swap: when `choose_one` picks a different block
    config than the planned one, the wrapper must rebuild the fused
    plan AND refresh `fused_prefill_stats` — the plan stays the
    (unit_plan, statics) 2-tuple every consumer unpacks, and the stats
    describe the NEW launch shape (the roofline cost model attributes
    from them; stale stats would attribute the old grid)."""
    import flashinfer_tpu as fi
    from flashinfer_tpu import autotuner
    from flashinfer_tpu.ops.paged_prefill import block_candidates

    qo_lens, kv_lens = [24, 40], [48, 64]
    (qo_indptr, kv_page_indptr, kv_page_indices, q, kc, vc) = _setup(
        qo_lens, kv_lens, seed=3)
    q = q.astype(jnp.bfloat16)
    kc = kc.astype(jnp.bfloat16)
    vc = vc.astype(jnp.bfloat16)
    last_page = (np.asarray(kv_lens)
                 - (np.asarray([np.ceil(l / PS) for l in kv_lens],
                               np.int32) - 1) * PS).astype(np.int32)
    w = fi.BatchPrefillWithPagedKVCacheWrapper(
        kv_layout="HND", backend="pallas_fused")
    w.plan(qo_indptr, kv_page_indptr, kv_page_indices, last_page,
           HQ, HKV, D, PS, causal=True)
    cfg0 = w.fused_prefill_config
    stats0 = w.fused_prefill_stats
    assert cfg0 is not None and stats0 is not None

    other = next(
        c for c in block_candidates(PS)
        if (int(c[0]), int(c[1]))
        != (cfg0["block_q"], cfg0["pages_per_chunk"]))
    monkeypatch.setattr(
        autotuner.AutoTuner, "choose_one",
        lambda self, op, key, cands, runner, default=None, module=None:
        other)
    with autotuner.autotune():
        out = np.asarray(w.run(q, (kc, vc)), np.float32)

    cfg1 = w.fused_prefill_config
    assert (cfg1["block_q"], cfg1["pages_per_chunk"]) \
        == (int(other[0]), int(other[1]))
    stats1 = w.fused_prefill_stats
    assert stats1 != stats0  # per-config unit/tile/cell counts moved
    assert stats1["mxu_cells_valid"] == stats0["mxu_cells_valid"]
    # the swapped plan is still runnable and numerically right (vs the
    # gather fallback)
    ref = fi.BatchPrefillWithPagedKVCacheWrapper(
        kv_layout="HND", backend="xla")
    ref.plan(qo_indptr, kv_page_indptr, kv_page_indices, last_page,
             HQ, HKV, D, PS, causal=True)
    np.testing.assert_allclose(out, np.asarray(ref.run(q, (kc, vc)),
                                               np.float32),
                               rtol=3e-2, atol=3e-2)
