"""ALiBi (pos_encoding_mode="ALIBI") vs an independent numpy oracle.

Ported reference matrix: ``/root/reference/tests/attention/test_alibi.py``
(single decode + single prefill), extended to the batch wrappers.  The
oracle follows the reference helper's formula (bias = slope_h * kv_pos —
row-constant shifts cancel in softmax, so this equals the kernels'
``slope_h * (kv_pos - q_pos)``), with slopes from ``get_alibi_slopes``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import flashinfer_tpu as fi
from flashinfer_tpu.utils import get_alibi_slopes


def _oracle(q, k, v, mask, slopes):
    """[q, H, D] x [kv, H, D] dense ALiBi attention in f64."""
    qn = np.asarray(q, np.float64)
    kn = np.asarray(k, np.float64)
    vn = np.asarray(v, np.float64)
    ql, H, D = qn.shape
    s = np.einsum("qhd,khd->hqk", qn, kn) / np.sqrt(D)
    bias = np.asarray(slopes, np.float64)[:, None, None] * np.arange(
        kn.shape[0]
    )[None, None, :]
    s = s + bias
    s = np.where(mask[None], s, -np.inf)
    s = s - s.max(axis=-1, keepdims=True)
    p = np.exp(s)
    p /= p.sum(axis=-1, keepdims=True)
    return np.einsum("hqk,khd->qhd", p, vn)


@pytest.mark.parametrize("seq_len", [1, 81, 729])
@pytest.mark.parametrize("num_heads", [8, 12])
def test_single_decode_alibi(seq_len, num_heads):
    D = 128
    key = jax.random.PRNGKey(seq_len)
    q = jax.random.normal(key, (num_heads, D), jnp.float32)
    k = jax.random.normal(jax.random.fold_in(key, 1), (seq_len, num_heads, D),
                          jnp.float32)
    v = jax.random.normal(jax.random.fold_in(key, 2), (seq_len, num_heads, D),
                          jnp.float32)
    o = fi.single_decode_with_kv_cache(q, k, v, pos_encoding_mode="ALIBI")
    ref = _oracle(np.asarray(q)[None], k, v,
                  np.ones((1, seq_len), bool),
                  get_alibi_slopes(num_heads))[0]
    np.testing.assert_allclose(np.asarray(o, np.float32), ref,
                               rtol=1e-3, atol=1e-3)


@pytest.mark.parametrize("q_len,kv_len", [(1, 17), (17, 17), (17, 81),
                                          (81, 81)])
@pytest.mark.parametrize("causal", [False, True])
def test_single_prefill_alibi(q_len, kv_len, causal):
    H, D = 8, 128
    key = jax.random.PRNGKey(q_len * 1000 + kv_len)
    q = jax.random.normal(key, (q_len, H, D), jnp.float32)
    k = jax.random.normal(jax.random.fold_in(key, 1), (kv_len, H, D),
                          jnp.float32)
    v = jax.random.normal(jax.random.fold_in(key, 2), (kv_len, H, D),
                          jnp.float32)
    o = fi.single_prefill_with_kv_cache(
        q, k, v, causal=causal, pos_encoding_mode="ALIBI"
    )
    mask = np.ones((q_len, kv_len), bool)
    if causal:
        mask = np.tril(mask, k=kv_len - q_len)
    ref = _oracle(q, k, v, mask, get_alibi_slopes(H))
    np.testing.assert_allclose(np.asarray(o, np.float32), ref,
                               rtol=1e-2, atol=1e-2)


def test_batch_decode_alibi_wrapper():
    """plan(pos_encoding_mode='ALIBI') reaches the dense path with the
    decode-form bias; compared per request against the oracle."""
    B, HQ, HKV, D, PS = 3, 8, 8, 128, 8
    lens = [24, 8, 17]
    pages_per = [(x + PS - 1) // PS for x in lens]
    total_pages = sum(pages_per)
    key = jax.random.PRNGKey(0)
    kc = jax.random.normal(key, (total_pages, HKV, PS, D), jnp.float32)
    vc = jax.random.normal(jax.random.fold_in(key, 1),
                           (total_pages, HKV, PS, D), jnp.float32)
    q = jax.random.normal(jax.random.fold_in(key, 2), (B, HQ, D),
                          jnp.float32)
    indptr = np.concatenate([[0], np.cumsum(pages_per)]).astype(np.int32)
    last = np.asarray([x - (p - 1) * PS for x, p in zip(lens, pages_per)],
                      np.int32)
    w = fi.BatchDecodeWithPagedKVCacheWrapper(kv_layout="HND")
    w.plan(indptr, np.arange(total_pages, dtype=np.int32), last,
           HQ, HKV, D, PS, pos_encoding_mode="ALIBI")
    o = np.asarray(w.run(q, (kc, vc)), np.float32)
    slopes = get_alibi_slopes(HQ)
    kflat = np.asarray(jnp.swapaxes(kc, 1, 2)).reshape(-1, HKV, D)
    vflat = np.asarray(jnp.swapaxes(vc, 1, 2)).reshape(-1, HKV, D)
    for b in range(B):
        rows = slice(int(indptr[b]) * PS, int(indptr[b]) * PS + lens[b])
        ref = _oracle(np.asarray(q[b])[None], kflat[rows], vflat[rows],
                      np.ones((1, lens[b]), bool), slopes)[0]
        np.testing.assert_allclose(o[b], ref, rtol=1e-3, atol=1e-3,
                                   err_msg=f"request {b}")


def test_batch_ragged_prefill_alibi_wrapper():
    B, H, D = 2, 8, 128
    qo = np.array([0, 13, 30], np.int32)
    kv = np.array([0, 29, 62], np.int32)
    key = jax.random.PRNGKey(3)
    q = jax.random.normal(key, (int(qo[-1]), H, D), jnp.float32)
    k = jax.random.normal(jax.random.fold_in(key, 1), (int(kv[-1]), H, D),
                          jnp.float32)
    v = jax.random.normal(jax.random.fold_in(key, 2), (int(kv[-1]), H, D),
                          jnp.float32)
    w = fi.BatchPrefillWithRaggedKVCacheWrapper()
    w.plan(qo, kv, H, H, D, causal=True, pos_encoding_mode="ALIBI")
    o = np.asarray(w.run(q, k, v), np.float32)
    slopes = get_alibi_slopes(H)
    for b in range(B):
        qs, ks = slice(qo[b], qo[b + 1]), slice(kv[b], kv[b + 1])
        ql, kl = int(qo[b + 1] - qo[b]), int(kv[b + 1] - kv[b])
        mask = np.tril(np.ones((ql, kl), bool), k=kl - ql)
        ref = _oracle(np.asarray(q)[qs], np.asarray(k)[ks],
                      np.asarray(v)[ks], mask, slopes)
        np.testing.assert_allclose(o[qs], ref, rtol=1e-2, atol=1e-2,
                                   err_msg=f"request {b}")


def test_batch_paged_prefill_alibi_wrapper():
    """ALiBi forces the paged wrapper off the fused kernel onto the
    gathered dense path (plan-time `use_fused` gate)."""
    B, H, D, PS = 2, 8, 128, 8
    qo = np.array([0, 13, 30], np.int32)
    kv_lens = [29, 33]
    pages_per = [(x + PS - 1) // PS for x in kv_lens]
    kv_pages = np.concatenate([[0], np.cumsum(pages_per)]).astype(np.int32)
    total_pages = int(kv_pages[-1])
    key = jax.random.PRNGKey(7)
    kc = jax.random.normal(key, (total_pages, H, PS, D), jnp.float32)
    vc = jax.random.normal(jax.random.fold_in(key, 1),
                           (total_pages, H, PS, D), jnp.float32)
    q = jax.random.normal(jax.random.fold_in(key, 2), (int(qo[-1]), H, D),
                          jnp.float32)
    last = np.asarray(
        [x - (p - 1) * PS for x, p in zip(kv_lens, pages_per)], np.int32
    )
    w = fi.BatchPrefillWithPagedKVCacheWrapper(kv_layout="HND")
    w.plan(qo, kv_pages, np.arange(total_pages, dtype=np.int32), last,
           H, H, D, PS, causal=True, pos_encoding_mode="ALIBI")
    assert w._fused_plan is None  # dense path forced
    o = np.asarray(w.run(q, (kc, vc)), np.float32)
    slopes = get_alibi_slopes(H)
    kflat = np.asarray(jnp.swapaxes(kc, 1, 2)).reshape(-1, H, D)
    vflat = np.asarray(jnp.swapaxes(vc, 1, 2)).reshape(-1, H, D)
    for b in range(B):
        qs = slice(int(qo[b]), int(qo[b + 1]))
        rows = slice(int(kv_pages[b]) * PS,
                     int(kv_pages[b]) * PS + kv_lens[b])
        ql, kl = int(qo[b + 1] - qo[b]), kv_lens[b]
        mask = np.tril(np.ones((ql, kl), bool), k=kl - ql)
        ref = _oracle(np.asarray(q)[qs], kflat[rows], vflat[rows], mask,
                      slopes)
        np.testing.assert_allclose(o[qs], ref, rtol=1e-2, atol=1e-2,
                                   err_msg=f"request {b}")


def test_alibi_mode_validation():
    """Typos raise (reference PosEncodingMode[...] KeyError), never fall
    through to unpositioned attention; ROPE_LLAMA is a valid honored mode
    (tests/test_rope_mode.py pins its numerics)."""
    q = jnp.zeros((8, 128), jnp.float32)
    k = jnp.zeros((4, 8, 128), jnp.float32)
    out = fi.single_prefill_with_kv_cache(
        jnp.zeros((4, 8, 128)), k, k, pos_encoding_mode="ROPE_LLAMA"
    )
    assert out.shape == (4, 8, 128)
    with pytest.raises(KeyError):
        fi.single_decode_with_kv_cache(q, k, k, pos_encoding_mode="ALIBI ")
    with pytest.raises(KeyError):
        fi.single_prefill_with_kv_cache(
            jnp.zeros((4, 8, 128)), k, k, pos_encoding_mode="ROPE"
        )


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("hkv", [8, 2])
def test_alibi_flash_kernel_matches_dense(causal, hkv):
    """The in-kernel ALiBi bias (explicit backend='pallas', SMEM slope
    per grid head) must match the dense xla path — interpret mode here,
    on-chip in the hardware tier.  GQA case included (slopes are per QO
    head, the kv head map is h // group)."""
    q_len, kv_len, H, D = 64, 160, 8, 128
    key = jax.random.PRNGKey(11)
    q = jax.random.normal(key, (q_len, H, D), jnp.float32)
    k = jax.random.normal(jax.random.fold_in(key, 1), (kv_len, hkv, D),
                          jnp.float32)
    v = jax.random.normal(jax.random.fold_in(key, 2), (kv_len, hkv, D),
                          jnp.float32)
    o_kernel = fi.single_prefill_with_kv_cache(
        q, k, v, causal=causal, pos_encoding_mode="ALIBI", backend="pallas"
    )
    o_dense = fi.single_prefill_with_kv_cache(
        q, k, v, causal=causal, pos_encoding_mode="ALIBI"
    )
    np.testing.assert_allclose(
        np.asarray(o_kernel, np.float32), np.asarray(o_dense, np.float32),
        rtol=2e-3, atol=2e-3,
    )


def test_alibi_dense_memory_guard():
    """A long-context ALiBi prefill must fail with instructions, not an
    opaque device OOM (dense logits cap)."""
    from flashinfer_tpu.prefill import _check_alibi_dense_size

    _check_alibi_dense_size(8, 4096, 4096)  # fine
    with pytest.raises(NotImplementedError, match="dense path"):
        _check_alibi_dense_size(32, 65536, 65536)
