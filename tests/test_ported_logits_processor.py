"""Migration proof #5: mechanical port of the reference test file
``/root/reference/tests/utils/test_logits_processor.py`` — the
LogitsPipe mini-compiler: compile=True vs compile=False equivalence
(TestLogitsPipeCompilation) and pipe-vs-direct-sampling-op equivalence
(TestLogitsPipeVsSamplingOps), with input_type=PROBS mid-stream pipes.

Deviations (written reasons): explicit PRNG keys replace torch
generators (``generator=`` is loudly rejected by the pipe);
``is_deterministic`` is accepted-inert (XLA reductions are
deterministic); matrix sampling via the shared 1/48 rank sampler with
the 2^25 element cap from the sampling port."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import flashinfer_tpu as fi
from flashinfer_tpu.logits_processor import (
    LogitsPipe,
    MinP,
    Sample,
    Softmax,
    Temperature,
    TensorType,
    TopK,
    TopP,
)
from tests.test_ported_batch_prefill import _sample
from tests.test_ported_sampling import _DISTS, _mem_gate


class TestLogitsPipeCompilation:
    """Reference TestLogitsPipeCompilation: compile=True == compile=False."""

    @pytest.mark.parametrize(
        "batch_size,vocab_size,distribution,temperature",
        _sample("lp_temp_softmax", [1, 99, 989], [111, 32000, 128256],
                _DISTS, [1.0, 0.5, 0.1]),
    )
    def test_temperature_softmax(self, batch_size, vocab_size,
                                 distribution, temperature):
        _mem_gate(batch_size, vocab_size)
        logits = distribution((batch_size, vocab_size),
                              jax.random.PRNGKey(42))
        pipe_c = LogitsPipe([Temperature(), Softmax()], compile=True)
        pipe_e = LogitsPipe([Temperature(), Softmax()], compile=False)
        a = pipe_c(logits, temperature=temperature)
        b = pipe_e(logits, temperature=temperature)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5)

    @pytest.mark.parametrize(
        "batch_size,vocab_size,p",
        _sample("lp_topp_c", [1, 99, 989], [111, 32000, 128256],
                [0.1, 0.5, 0.9]),
    )
    def test_topp(self, batch_size, vocab_size, p):
        _mem_gate(batch_size, vocab_size)
        pre = jax.random.uniform(jax.random.PRNGKey(42),
                                 (batch_size, vocab_size))
        probs = pre / pre.sum(-1, keepdims=True)
        pipe_c = LogitsPipe([TopP()], compile=True,
                            input_type=TensorType.PROBS)
        pipe_e = LogitsPipe([TopP()], compile=False,
                            input_type=TensorType.PROBS)
        a = pipe_c(probs, top_p=p, is_deterministic=True)
        b = pipe_e(probs, top_p=p, is_deterministic=True)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6)


class TestLogitsPipeVsSamplingOps:
    """Reference TestLogitsPipeVsSamplingOps: a pipe must reproduce the
    direct sampling op it legalizes to."""

    @pytest.mark.parametrize(
        "batch_size,vocab_size,temperature,temperature_arr",
        _sample("lp_vs_softmax", [1, 99, 989], [111, 32000, 128256],
                [1.0, 0.5, 0.1], [True, False]),
    )
    def test_temperature_softmax(self, batch_size, vocab_size,
                                 temperature, temperature_arr):
        _mem_gate(batch_size, vocab_size)
        logits = jax.random.normal(jax.random.PRNGKey(42),
                                   (batch_size, vocab_size))
        if temperature_arr:
            temperature = jax.random.uniform(jax.random.PRNGKey(1),
                                             (batch_size,))
        direct = fi.sampling.softmax(logits, temperature=temperature)
        pipe = LogitsPipe([Temperature(), Softmax()])
        out = pipe(logits, temperature=temperature)
        np.testing.assert_allclose(np.asarray(out), np.asarray(direct),
                                   atol=1e-5)

    @pytest.mark.parametrize(
        "batch_size,vocab_size,p",
        _sample("lp_vs_topp", [1, 99, 989], [111, 32000, 128256],
                [0.1, 0.5, 0.9]),
    )
    def test_topp(self, batch_size, vocab_size, p):
        _mem_gate(batch_size, vocab_size)
        pre = jax.random.uniform(jax.random.PRNGKey(42),
                                 (batch_size, vocab_size))
        probs = pre / pre.sum(-1, keepdims=True)
        direct = fi.sampling.top_p_renorm_probs(probs, p)
        pipe = LogitsPipe([TopP()], input_type=TensorType.PROBS)
        out = pipe(probs, top_p=p, is_deterministic=True)
        assert (np.asarray(out) == np.asarray(direct)).all()

    @pytest.mark.parametrize(
        "batch_size,vocab_size,k",
        _sample("lp_vs_topk_p", [1, 99, 989], [111, 32000, 128256],
                [10, 100, 500]),
    )
    def test_probs_topk(self, batch_size, vocab_size, k):
        if k > vocab_size:
            pytest.skip("k should be less than vocab_size")
        _mem_gate(batch_size, vocab_size)
        pre = jax.random.uniform(jax.random.PRNGKey(42),
                                 (batch_size, vocab_size))
        probs = pre / pre.sum(-1, keepdims=True)
        direct = fi.sampling.top_k_renorm_probs(probs, k)
        pipe = LogitsPipe([TopK()], input_type=TensorType.PROBS)
        out = pipe(probs, top_k=k)
        np.testing.assert_allclose(np.asarray(out), np.asarray(direct))

    @pytest.mark.parametrize(
        "batch_size,vocab_size,k",
        _sample("lp_vs_topk_l", [1, 99, 989], [111, 32000, 128256],
                [10, 100, 500]),
    )
    def test_logits_topk(self, batch_size, vocab_size, k):
        if k > vocab_size:
            pytest.skip("k should be less than vocab_size")
        _mem_gate(batch_size, vocab_size)
        logits = jax.random.normal(jax.random.PRNGKey(42),
                                   (batch_size, vocab_size))
        direct = fi.sampling.top_k_mask_logits(logits, k)
        pipe = LogitsPipe([TopK()])  # LOGITS stream -> mask legalization
        out = pipe(logits, top_k=k)
        np.testing.assert_allclose(np.asarray(out), np.asarray(direct))

    @pytest.mark.parametrize(
        "batch_size,vocab_size,p",
        _sample("lp_vs_minp", [1, 99, 989], [111, 32000, 128256],
                [0.05, 0.2, 0.7]),
    )
    def test_minp(self, batch_size, vocab_size, p):
        _mem_gate(batch_size, vocab_size)
        pre = jax.random.uniform(jax.random.PRNGKey(42),
                                 (batch_size, vocab_size))
        probs = pre / pre.sum(-1, keepdims=True)
        mp = jnp.full((batch_size,), float(p))
        pipe = LogitsPipe([MinP()], input_type=TensorType.PROBS)
        out = np.asarray(pipe(probs, min_p=mp))
        pn = np.asarray(probs, np.float64)
        keep = pn >= p * pn.max(-1, keepdims=True)
        ref = np.where(keep, pn, 0.0)
        ref = ref / ref.sum(-1, keepdims=True)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)

    @pytest.mark.parametrize(
        "batch_size,vocab_size",
        _sample("lp_sample", [1, 99, 989], [111, 32000, 128256]),
    )
    def test_full_pipe_sample(self, batch_size, vocab_size):
        """End-to-end pipe with Sample: same key as the direct chain
        gives identical tokens (the reference's cloned-generator check,
        exact with explicit keys)."""
        _mem_gate(batch_size, vocab_size)
        logits = jax.random.normal(jax.random.PRNGKey(42),
                                   (batch_size, vocab_size))
        key = jax.random.PRNGKey(9)
        pipe = LogitsPipe([Temperature(), Softmax(), TopP(), Sample()])
        toks = pipe(logits, key=key, temperature=0.7, top_p=0.9)
        probs = fi.sampling.softmax(logits, temperature=0.7)
        probs = fi.sampling.top_p_renorm_probs(probs, 0.9)
        direct = fi.sampling.sampling_from_probs(probs, key)
        assert (np.asarray(toks) == np.asarray(direct)).all()
        with pytest.raises(ValueError, match="PRNGKey"):
            pipe(logits, generator=object(), temperature=0.7, top_p=0.9)


def test_pipe_validation_errors():
    """Reference legalization rules: TopP on a LOGITS stream and ops
    after Sample are validation errors."""
    with pytest.raises(ValueError, match="Softmax"):
        LogitsPipe([TopP()])
    with pytest.raises(ValueError, match="already ended"):
        LogitsPipe([Softmax(), Sample(), TopP()])
    with pytest.raises(ValueError, match="input_type"):
        LogitsPipe([TopP()], input_type="tokens")
