"""Roofline attribution tests (ISSUE 5).

Pins the three halves of the attribution engine:

- ``obs.hwspec`` — the chip-spec registry is the single source of
  truth (VMEM caps, peaks, aliases, env-overridable detection);
- ``obs.costmodel`` — every formula is pinned against a brute-force
  count on tiny shapes (attention/MLA/gmm FLOPs + read/write bytes,
  quantized-KV byte widths, fused-prefill launched-vs-effective from a
  REAL ``build_prefill_work_units`` plan);
- ``obs.roofline`` — attribute/stamp math by hand, the bench-row
  schema contract (every bench.py routine stamps through the shared
  model), the auditor's roofline-fraction comparison space, and the
  ``obs perf`` doctor reproducing the round-5 VERDICT headline
  fractions from BENCH_BANKED.md with a schema-stable JSON form.

Plus the zero-overhead pin: plain library use (metrics off, no bench)
never imports the cost model at all.
"""

import json
import os
import re
import subprocess
import sys

import numpy as np
import pytest

from flashinfer_tpu.obs import bench_audit, costmodel, hwspec, roofline
from flashinfer_tpu.obs.costmodel import Cost

REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), os.pardir))


# ---------------------------------------------------------------------------
# hwspec: the registry
# ---------------------------------------------------------------------------


@pytest.mark.quick
def test_hwspec_registry_single_source_of_truth(monkeypatch):
    # VMEM_CAPS (what analysis L009 imports) is derived from the specs,
    # never a second literal table
    assert hwspec.VMEM_CAPS == {
        name: s.vmem_bytes for name, s in hwspec.CHIP_SPECS.items()}
    assert hwspec.VMEM_CAPS["v5e"] == 64 * 1024 * 1024
    assert hwspec.VMEM_CAPS["v5p"] == 128 * 1024 * 1024

    # lookup: canonical names, aliases, device-kind-ish strings, and a
    # never-raise fallback for unknown chips
    assert hwspec.spec("v5e").hbm_tbps == pytest.approx(0.819)
    assert hwspec.spec("TPU v5 lite").name == "v5e"
    assert hwspec.spec("TPU v5p").name == "v5p"
    assert hwspec.spec("trillium").name == "v6e"
    assert hwspec.spec("quantum-chip-9000").name == hwspec.DEFAULT_CHIP

    # peak mapping for pre-roofline banked rows (they carry only `peak`)
    assert hwspec.spec_for_peak_tbps(0.819).name == "v5e"
    assert hwspec.spec_for_peak_tbps(2.765).name == "v5p"
    assert hwspec.spec_for_peak_tbps(123.0) is None
    assert hwspec.spec_for_peak_tbps("garbage") is None

    # dtype normalization + ridge point
    v5e = hwspec.spec("v5e")
    assert v5e.peak_tflops("bfloat16") == pytest.approx(197.0)
    assert v5e.peak_tflops("float8_e4m3fn") == pytest.approx(394.0)
    assert v5e.peak_tflops("no_such_dtype") == pytest.approx(197.0)
    assert v5e.ridge_intensity("bf16") == pytest.approx(197.0 / 0.819)

    # detection: env override wins and works with no accelerator
    monkeypatch.setenv("FLASHINFER_TPU_CHIP", "v5p")
    assert hwspec.detect_chip() == "v5p"
    assert hwspec.current_spec().name == "v5p"
    monkeypatch.delenv("FLASHINFER_TPU_CHIP")
    assert hwspec.detect_chip(device_kind="TPU v6e") == "v6e"
    assert hwspec.detect_chip(device_kind="cpu") == hwspec.DEFAULT_CHIP

    # docs table covers every registered chip
    table = hwspec.registry_table()
    assert len(table) == len(hwspec.CHIP_SPECS) + 1


def test_hwspec_import_is_side_effect_free():
    """The lint path (analysis L009) imports hwspec in accelerator-free
    processes: importing it must read no env and touch no backend."""
    src = open(os.path.join(
        REPO_ROOT, "flashinfer_tpu", "obs", "hwspec.py")).read()
    body = src.split('"""', 2)[2]  # strip the module docstring
    for needle in ("os.environ", "jax.devices", "import jax"):
        hits = [ln for ln in body.splitlines()
                if needle in ln and not ln.lstrip().startswith("#")]
        # only inside function bodies (indented), never at module level
        assert all(ln.startswith((" ", "\t")) for ln in hits), needle


# ---------------------------------------------------------------------------
# costmodel: formulas vs brute force on tiny shapes
# ---------------------------------------------------------------------------


def _brute_attention(qo, kv, hq, hkv, dqk, dvo, causal, *, batch=1,
                     qb=2, kvb=2, ob=2, window_left=-1):
    """Independent per-(q, kv)-pair count: 2 FLOPs per madd, QK^T over
    dqk plus PV over dvo; every operand read once, output written once."""
    att = 0
    off = kv - qo
    for qi in range(qo):
        hi = min(qi + off, kv - 1) if causal else kv - 1
        lo = max(qi + off - window_left, 0) if window_left >= 0 else 0
        att += max(hi - lo + 1, 0)
    flops = 2.0 * batch * att * hq * (dqk + dvo)
    bread = batch * (qo * hq * dqk * qb + kv * hkv * (dqk + dvo) * kvb)
    bwrite = batch * qo * hq * dvo * ob
    return flops, float(bread), float(bwrite)


@pytest.mark.quick
@pytest.mark.parametrize("qo,kv,causal", [
    (1, 7, False), (5, 5, True), (3, 11, True), (8, 8, False),
    (7, 16, True), (1, 1, True),
])
def test_costmodel_attention_pinned_against_brute_force(qo, kv, causal):
    hq, hkv, dqk, dvo = 4, 2, 8, 6
    c = costmodel.attention(qo, kv, hq, hkv, dqk, dvo, causal=causal,
                            batch=3)
    f, br, bw = _brute_attention(qo, kv, hq, hkv, dqk, dvo, causal,
                                 batch=3)
    assert c.flops == pytest.approx(f)
    assert c.bytes_read == pytest.approx(br)
    assert c.bytes_written == pytest.approx(bw)
    assert c.flops_effective is None  # plain attention has no waste


@pytest.mark.parametrize("qo,kv,causal,window", [
    (4, 9, True, -1), (4, 9, False, -1), (6, 6, True, 2), (3, 8, False, 4),
])
def test_attended_tokens_matches_dense_mask(qo, kv, causal, window):
    """attended_tokens (the counted term of every attention formula)
    against an explicit dense mask with the bottom-right alignment."""
    off = kv - qo
    mask = np.ones((qo, kv), bool)
    for qi in range(qo):
        for ki in range(kv):
            if causal and ki > qi + off:
                mask[qi, ki] = False
            if window >= 0 and ki < qi + off - window:
                mask[qi, ki] = False
    assert costmodel.attended_tokens(
        qo, kv, causal=causal, window_left=window) == int(mask.sum())


def test_costmodel_quantized_kv_byte_widths():
    """int8/fp8 caches shrink ONLY the kv stream, by exactly the byte
    ratio — the decode win the int8-cache bench measured."""
    bs, ctx, hq, hkv, d = 4, 32, 8, 2, 16
    bf16 = costmodel.paged_decode(bs, ctx, hq, hkv, d, kv_bytes=2)
    int8 = costmodel.paged_decode(bs, ctx, hq, hkv, d, kv_bytes=1)
    kv_stream = bs * ctx * hkv * (d + d)  # tokens x heads x (k+v dims)
    assert bf16.bytes_read - int8.bytes_read == pytest.approx(kv_stream)
    assert bf16.flops == int8.flops  # compute in bf16 either way
    assert bf16.bytes_written == int8.bytes_written
    # decode == single-token attention over the whole cache
    f, br, bw = _brute_attention(1, ctx, hq, hkv, d, d, False,
                                 batch=bs)
    assert bf16.flops == pytest.approx(f)
    assert bf16.bytes_total == pytest.approx(br + bw)


def test_costmodel_mla_decode_brute_force():
    """MLA absorbed decode: latent cache read ONCE for all heads, kpe
    lane-padded to 128 columns (real HBM traffic), FLOPs over the live
    512+64 / 512 dims only."""
    bs, ctx, h, dc, dp = 3, 16, 4, 32, 8
    c = costmodel.mla_decode(bs, ctx, h, latent_dim=dc, rope_dim=dp,
                             lane_pad=16)
    flops = 0.0
    for _ in range(bs):
        for _ in range(ctx):
            for _ in range(h):
                flops += 2 * (dc + dp) + 2 * dc  # q.k then p.v madds
    assert c.flops == pytest.approx(flops)
    # cache streams once per request (NOT per head) at padded width
    assert c.bytes_read == pytest.approx(
        bs * ctx * (dc + 16) * 2 + bs * h * (dc + dp) * 2)
    assert c.bytes_written == pytest.approx(bs * h * dc * 2)
    # the defaults match the DeepSeek layout the bench measures
    d = costmodel.mla_decode(1, 1, 1)
    assert d.flops == pytest.approx(2 * (512 + 64) + 2 * 512)


def test_costmodel_moe_gmm_brute_force():
    tokens, e, h, i, k = 5, 4, 8, 12, 2
    c = costmodel.moe_gmm(tokens, e, h, i, k)
    flops = 0.0
    for _ in range(tokens):
        for _ in range(k):  # each routed choice runs both GEMMs
            flops += 2 * (h * (2 * i)) + 2 * (i * h)
    assert c.flops == pytest.approx(flops)
    # weight traffic: every hot expert streamed once
    hot = min(e, tokens * k)
    assert c.bytes_read >= hot * (h * 2 * i + i * h) * 2
    int8 = costmodel.moe_gmm(tokens, e, h, i, k, weight_bytes=1,
                             dtype="int8")
    assert c.bytes_read - int8.bytes_read == pytest.approx(
        hot * (h * 2 * i + i * h))
    assert int8.dtype == "int8"


def test_costmodel_gemm_norm_rope_sampling_shapes():
    g = costmodel.gemm(3, 5, 7)
    assert g.flops == pytest.approx(2 * 3 * 5 * 7)
    assert g.bytes_read == pytest.approx((3 * 7 + 7 * 5) * 2)
    assert g.bytes_written == pytest.approx(3 * 5 * 2)
    n = costmodel.norm(4, 8)
    assert n.bytes_read == pytest.approx((4 * 8 + 8) * 2)
    r = costmodel.rope(4, 2, 8, quantize_out_bytes=1)
    assert r.bytes_written == pytest.approx(4 * 2 * 8)  # fp8 out width
    s = costmodel.sampling(2, 100)
    assert s.bytes_read == pytest.approx(2 * 100 * 4)  # f32 probs pass
    assert 0 < s.intensity < 1  # bandwidth attribution, not MFU claim


def test_fused_prefill_launched_vs_effective_from_real_plan():
    """Launched/effective work straight from a REAL work-unit plan's
    stats (the PR 3 planner), pinned against brute-force cell counts."""
    from flashinfer_tpu.ops.paged_prefill import build_prefill_work_units

    page, bq, ppc = 2, 4, 2
    qo_lens, kv_lens = [5, 3], [8, 6]
    qo_indptr = np.cumsum([0] + qo_lens).astype(np.int64)
    pages_per = [(kv + page - 1) // page for kv in kv_lens]
    kv_page_indptr = np.cumsum([0] + pages_per).astype(np.int64)
    kv_page_indices = np.arange(kv_page_indptr[-1], dtype=np.int64)

    plan = build_prefill_work_units(
        qo_indptr, kv_page_indptr, kv_page_indices,
        np.asarray(kv_lens, np.int64), bq, ppc, page, causal=False)
    stats = plan["stats"]
    chunk = ppc * page
    # non-causal, nothing prunable: every in-bounds (row, kv-col) cell
    # is useful, so valid cells == the attended-pair count exactly
    assert stats["mxu_cells_valid"] == sum(
        q * kv for q, kv in zip(qo_lens, kv_lens))
    assert stats["mxu_cells_total"] == stats["units"] * bq * chunk
    assert stats["mxu_cells_total"] >= stats["mxu_cells_valid"]

    hq, hkv, d = 4, 2, 8
    c = costmodel.fused_prefill_from_stats(
        stats, block_q=bq, pages_per_chunk=ppc, page_size=page,
        num_qo_heads=hq, num_kv_heads=hkv, head_dim=d,
        total_q=sum(qo_lens))
    per_cell = 2 * hq * (d + d)
    assert c.flops == pytest.approx(stats["mxu_cells_total"] * per_cell)
    assert c.flops_effective == pytest.approx(
        stats["mxu_cells_valid"] * per_cell)
    assert c.flops_effective <= c.flops
    # q streams once per packed tile, kv once per unit chunk
    assert c.bytes_read == pytest.approx(
        stats["tiles"] * bq * hq * d * 2
        + stats["units"] * chunk * hkv * (d + d) * 2)

    # causal pruning: fewer (or equal) launched units, and the wrapper
    # formula reports effective == true attended work, < launched
    causal = build_prefill_work_units(
        qo_indptr, kv_page_indptr, kv_page_indices,
        np.asarray(kv_lens, np.int64), bq, ppc, page, causal=True)
    assert causal["stats"]["units"] <= stats["units"]
    pc = costmodel.paged_prefill(
        1, qo_lens[0], kv_lens[0], hq, hkv, d, causal=True,
        stats=causal["stats"], block_q=bq, pages_per_chunk=ppc,
        page_size=page)
    f_eff, _, _ = _brute_attention(qo_lens[0], kv_lens[0], hq, hkv, d, d,
                                   True)
    assert pc.flops_effective == pytest.approx(f_eff)
    assert pc.flops_effective < pc.flops


def test_serving_step_is_sum_of_phases():
    shape = costmodel.SERVING_SHAPES["llama70b_tp8shard_int8"]
    phases = costmodel.serving_phase_costs(8, 256, 4, **shape)
    assert set(phases) == set(costmodel.SERVING_PHASES)
    full = costmodel.serving_step(8, 256, 4, **shape)
    fitted = costmodel.serving_step(8, 256, 4, include_kv_append=False,
                                    include_sampling=False, **shape)
    total = sum(p.flops for p in phases.values())
    assert full.flops == pytest.approx(total)
    assert fitted.bytes_total == pytest.approx(
        full.bytes_total - phases["kv_append"].bytes_total
        - phases["sampling"].bytes_total)
    assert full.dtype == "int8"  # attributes against the int8 peak


def test_cost_for_bench_row_reconstructs_pre_roofline_rows():
    """Rows banked before cost stamping attribute via the fixed bench
    shapes; stamped rows use their own fields verbatim (and win)."""
    rec = costmodel.cost_for_bench_row(
        {"phase": "decode", "bs": 64, "ctx": 4096, "us": 1000.0})
    assert rec is not None
    cost, seconds = rec
    assert seconds == pytest.approx(1e-3)
    assert cost.flops == costmodel.paged_decode(64, 4096, 32, 8, 128).flops

    stamped = costmodel.cost_for_bench_row(
        {"phase": "decode", "bs": 64, "ctx": 4096, "us": 1000.0,
         "flops": 5.0, "bytes_read": 7.0, "bytes_written": 3.0,
         "flops_effective": 4.0, "dtype": "int8"})
    cost, _ = stamped
    assert (cost.flops, cost.bytes_read, cost.bytes_written) == (5, 7, 3)
    assert cost.flops_effective == 4.0 and cost.dtype == "int8"

    assert costmodel.cost_for_bench_row({"phase": "selftest", "n": 1}) \
        is None  # the CI stub has no model, and that is fine
    assert costmodel.cost_for_bench_row({"phase": "decode"}) is None


# ---------------------------------------------------------------------------
# roofline: attribution math + the row stamp
# ---------------------------------------------------------------------------


@pytest.mark.quick
def test_roofline_attribute_math_by_hand():
    v5e = hwspec.spec("v5e")
    # memory-bound: intensity 2 flops/byte, far below the ~240 ridge
    c = Cost(flops=2.0e12, bytes_read=0.8e12, bytes_written=0.2e12)
    r = roofline.attribute(c, 10.0, v5e)
    assert r.bound == "memory"
    assert r.achieved_tbps == pytest.approx(0.1)
    assert r.achieved_tflops == pytest.approx(0.2)
    t_mem = 1.0e12 / (0.819e12)
    assert r.pct_roofline == pytest.approx(t_mem / 10.0)
    assert r.effective_pct_roofline == pytest.approx(r.pct_roofline)
    assert r.mfu == pytest.approx(0.2 / 197.0)
    assert r.intensity == pytest.approx(2.0)
    assert r.ridge == pytest.approx(197.0 / 0.819)

    # compute-bound at the int8 peak
    c = Cost(flops=394e12, bytes_read=1e9, bytes_written=0, dtype="int8")
    r = roofline.attribute(c, 2.0, v5e)
    assert r.bound == "compute"
    assert r.pct_roofline == pytest.approx(0.5)
    assert r.peak_tflops == pytest.approx(394.0)

    # effective work: waste shows up ONLY in the effective fraction
    c = Cost(flops=100e12, bytes_read=1e9, bytes_written=0,
             flops_effective=50e12)
    r = roofline.attribute(c, 1.0, v5e)
    assert r.effective_pct_roofline == pytest.approx(
        r.pct_roofline / 2.0)
    assert r.achieved_tflops_effective == pytest.approx(50.0)

    with pytest.raises(ValueError):
        roofline.attribute(c, 0.0, v5e)


def test_stamp_row_canonical_schema():
    row = {"phase": "prefill", "us": 100.0}
    cost = Cost(flops=1e9, bytes_read=1e6, bytes_written=1e5,
                flops_effective=8e8)
    out = roofline.stamp_row(row, cost, 1e-4, hwspec.spec("v5e"))
    assert out is row  # in place
    assert set(roofline.ROW_FIELDS) <= set(row)
    assert row["flops_effective"] == pytest.approx(8e8)
    assert row["bound"] in ("memory", "compute")
    assert 0 < row["pct_roofline"]
    assert row["effective_pct_roofline"] <= row["pct_roofline"]
    # no waste -> no redundant effective field on the banked row
    row2 = roofline.stamp_row({}, Cost(1e9, 1e6, 1e5), 1e-4,
                              hwspec.spec("v5e"))
    assert "flops_effective" not in row2


def test_spec_for_row_chip_then_peak_then_default():
    assert roofline.spec_for_row({"chip": "v5p"}).name == "v5p"
    assert roofline.spec_for_row({"peak": 0.819}).name == "v5e"
    assert roofline.spec_for_row({}).name == hwspec.DEFAULT_CHIP
    assert roofline.spec_for_row(
        {}, default=hwspec.spec("v6e")).name == "v6e"


def test_bench_rows_all_stamped_by_shared_model():
    """The schema contract, enforced structurally: every `_emit_row`
    call in bench.py routes through `_stamp` (the shared cost model),
    except the device-free CI selftest stub; and no inline peak-spec
    arithmetic survives anywhere in the file."""
    src = open(os.path.join(REPO_ROOT, "bench.py")).read()
    calls = [m for m in re.finditer(r"_emit_row\((?!\*\*_stamp\()", src)
             if "def _emit_row" not in
             src[src.rfind("\n", 0, m.start()) + 1: m.end()]]
    unstamped = [src[m.start(): m.start() + 60].splitlines()[0]
                 for m in calls]
    assert all("selftest" in u for u in unstamped), unstamped
    for forbidden in ("HBM_PEAK_TBPS", "chip_peak_tbps",
                      "attention_flops", "attention_bytes"):
        assert forbidden not in src, forbidden
    # the stamped field set is the documented one
    assert set(roofline.ROW_FIELDS) >= {
        "flops", "bytes_read", "bytes_written", "intensity", "bound",
        "pct_roofline", "effective_pct_roofline"}


def test_timeline_phase_mfu_joins_profiler_spans():
    spec = hwspec.spec("v5e")
    costs = {"attention": Cost(flops=1e9, bytes_read=1e8,
                               bytes_written=1e7)}
    events = [{"name": "serving.attention", "dur": 0.5e-3},
              {"name": "serving.attention", "dur": 0.5e-3},
              {"name": "serving.unmodeled", "dur": 1.0}]
    out = roofline.timeline_phase_mfu(events, costs, spec)
    assert set(out) == {"attention"}  # only phases with a cost
    assert out["attention"]["dur_s"] == pytest.approx(1e-3)  # summed
    assert out["attention"]["mfu"] == pytest.approx(
        1e9 / 1e-3 / 1e12 / 197.0, abs=1e-4)  # report rounds to 4 places


# ---------------------------------------------------------------------------
# bench_audit: the roofline-fraction comparison space
# ---------------------------------------------------------------------------


def test_roofline_fraction_normalizes_legacy_percent_rows():
    # pre-roofline scans rows banked PERCENT under the same field name
    # (no stamp fields ride along); stamped rows carry a 0..1 fraction.
    # Magnitude can't tell them apart — the banked history has a
    # 0.6-PERCENT gdn_decode artifact row that a >2.0 cutoff would
    # misread as a winning 0.6 fraction — so the stamp's presence does.
    assert bench_audit.roofline_fraction({"pct_roofline": 49.0}) \
        == pytest.approx(0.49)
    assert bench_audit.roofline_fraction({"pct_roofline": 0.6}) \
        == pytest.approx(0.006)  # the real banked artifact shape
    assert bench_audit.roofline_fraction(
        {"pct_roofline": 0.9, "bound": "memory"}) == pytest.approx(0.9)
    assert bench_audit.roofline_fraction(
        {"pct_roofline": 0.6, "chip": "v5e"}) == pytest.approx(0.6)
    assert bench_audit.roofline_fraction({"pct_roofline": 0}) is None
    assert bench_audit.roofline_fraction({}) is None


def test_auditor_compares_in_roofline_fraction_space_across_chips():
    """A v5p row must compete with the v5e history for the same
    configuration in fraction-of-own-roofline space — raw TB/s would
    call a 3x-faster chip 'ok' even when its kernel regressed."""
    hist = [{"phase": "decode", "bs": 64, "ctx": 4096, "tbps": 0.73,
             "pct_roofline": 0.89, "chip": "v5e"}]
    aud = bench_audit.RowAuditor(hist)
    # same fraction on the faster chip: ok, despite 3x the raw number
    good = aud.stamp({"phase": "decode", "bs": 64, "ctx": 4096,
                      "tbps": 2.4, "pct_roofline": 0.87, "chip": "v5p"})
    assert good["quality"] == "ok"
    assert good["vs_best_roofline"] == pytest.approx(0.87 / 0.89,
                                                     abs=1e-3)
    # 3x the raw v5e number but a collapsed fraction: poison — the raw
    # rule alone would have waved this regression through
    bad = bench_audit.RowAuditor(hist).stamp(
        {"phase": "decode", "bs": 64, "ctx": 4096, "tbps": 2.4,
         "pct_roofline": 0.25, "chip": "v5p"})
    assert bad["quality"] == "poison"


def test_auditor_poisons_measurements_above_the_hardware_ceiling():
    aud = bench_audit.RowAuditor()
    fast = aud.stamp({"phase": "serving", "bs": 64, "ctx": 4096,
                      "tbps": 1.6, "pct_roofline": 1.95,
                      "chip": "v5e"})
    assert fast["quality"] == "poison"
    # and the artifact never becomes the baseline best
    ok = aud.stamp({"phase": "serving", "bs": 64, "ctx": 4096,
                    "tbps": 0.7, "pct_roofline": 0.85, "chip": "v5e"})
    assert ok["quality"] == "ok"
    assert "vs_best_roofline" not in ok


def test_auditor_legacy_percent_artifact_rows_stay_poison():
    """The real banked shape the magnitude heuristic would misread: a
    gdn_decode row banked at 0.6 PERCENT of roofline (an artifact, raw
    gbps ~1% of best) must NOT read as a 0.60 fraction that beats the
    genuine ~0.52-0.58 history and re-audit 'ok'."""
    hist = [
        {"phase": "scans", "op": "gdn_decode", "B": 64, "gbps": 473.9,
         "pct_roofline": 57.9},  # genuine legacy row: 57.9 percent
        {"phase": "scans", "op": "gdn_decode", "B": 64, "gbps": 4.6,
         "pct_roofline": 0.6},  # artifact legacy row: 0.6 percent
    ]
    aud = bench_audit.RowAuditor(hist)
    bad = aud.stamp(dict(hist[1]))
    assert bad["quality"] == "poison"
    good = aud.stamp(dict(hist[0]))
    assert good["quality"] == "ok"


def test_auditor_raw_rule_still_works_without_fractions():
    aud = bench_audit.RowAuditor([{"phase": "moe", "tokens": 64,
                                   "tflops": 100.0}])
    row = aud.stamp({"phase": "moe", "tokens": 64, "tflops": 30.0})
    assert row["quality"] == "poison"  # 0.3 < 0.35, the committed rule
    assert row["vs_best"] == pytest.approx(0.3)


def test_load_banked_history_strict_raises_on_malformed(tmp_path):
    p = tmp_path / "BANK.md"
    p.write_text("# notes\n```json\n{not json]\n```\n"
                 "```json\n{\"rows\": [{\"phase\": \"x\"}, 17]}\n```\n")
    rows = bench_audit.load_banked_history(str(p))  # tolerant default
    assert rows == [{"phase": "x"}]
    with pytest.raises(ValueError) as e:
        bench_audit.load_banked_history(str(p), strict=True)
    assert "malformed json block" in str(e.value)
    assert "non-dict row" in str(e.value)
    with pytest.raises(ValueError):
        bench_audit.load_banked_history(str(tmp_path / "absent.md"),
                                        strict=True)


# ---------------------------------------------------------------------------
# the `obs perf` doctor
# ---------------------------------------------------------------------------


def _stamped(phase, us, cost, spec_name="v5e", **cfg):
    row = dict(phase=phase, us=us, **cfg)
    return roofline.stamp_row(row, cost, us * 1e-6,
                              hwspec.spec(spec_name))


def test_build_perf_report_sections_on_synthetic_rows():
    v5e = hwspec.spec("v5e")
    # a decode cell at half roofline; seconds from the cost itself
    dc = costmodel.paged_decode(64, 4096, 32, 8, 128)
    t_us = dc.bytes_total / (0.819e12) / 0.5 * 1e6
    rows = [_stamped("decode", t_us, dc, bs=64, ctx=4096)]
    # a prefill row with padding waste
    pf = Cost(flops=4e12, bytes_read=1e10, bytes_written=1e9,
              flops_effective=3e12, op="paged_prefill")
    rows.append(_stamped("prefill", 40000.0, pf, kind="paged_chunked",
                         bs=8, qlen=512, ctx=4096))
    # an implausibly fast artifact (above the ceiling): a PRE-roofline
    # row (no stamp — the auditor can't see a fraction), so only the
    # report's reconstruction-side ceiling check can catch it
    dc8 = costmodel.paged_decode(64, 8192, 32, 8, 128)
    rows.append(dict(phase="decode", bs=64, ctx=8192,
                     us=dc8.bytes_total / 0.819e12 / 1.25 * 1e6))
    # an e2e serving row joining the measured phase decomposition
    shape = costmodel.SERVING_SHAPES["llama70b_tp8shard_int8"]
    phases = costmodel.serving_phase_costs(64, 4096, 4, **shape)
    decomp = {}
    for name, c in phases.items():
        t = roofline.attribute(c, 1.0, v5e)
        floor = max(c.bytes_total / 0.819e12,
                    c.flops / (v5e.peak_tflops(c.dtype) * 1e12))
        decomp[name + "_us"] = floor / 0.5 * 1e6  # half roofline each
    decomp["residual_us"] = 12.0
    step = costmodel.serving_step(64, 4096, 4, **shape)
    srow = dict(phase="serving", model="llama70b_tp8shard_int8",
                mode="e2e_measured", bs=64, ctx=4096, layers=4,
                us_step=sum(v for k, v in decomp.items()
                            if k != "residual_us") + 12.0,
                overhead_decomposition=decomp)
    roofline.stamp_row(srow, step, srow["us_step"] * 1e-6, v5e)
    rows.append(srow)

    rep = roofline.build_perf_report(rows)
    assert rep["schema"] == "flashinfer_tpu.obs.perf/6"
    assert rep["rows_total"] == 4
    assert rep["rows_implausible"] == 1  # the artifact was dropped
    assert rep["rows_attributed"] == 3
    assert "v5e" in rep["chips"]

    by_op = {o["op"]: o for o in rep["ops"]}
    assert by_op["decode"]["bound"] == "memory"
    assert by_op["decode"]["pct_roofline"]["best"] == pytest.approx(
        0.5, abs=0.01)
    assert sum(o["time_share"] for o in rep["ops"]) == pytest.approx(
        1.0, abs=0.01)

    # waste attribution picked up the launched-vs-effective split
    assert len(rep["waste"]) == 1
    assert rep["waste"][0]["waste_pct"] == pytest.approx(25.0)

    # per-phase serving MFU joined every measured phase
    assert len(rep["serving_phase_mfu"]) == 1
    sp = rep["serving_phase_mfu"][0]
    assert set(sp["phases"]) == set(costmodel.SERVING_PHASES)
    for p in sp["phases"].values():
        assert p["pct_roofline"] == pytest.approx(0.5, abs=0.02)
    assert sp["residual_us"] == 12.0

    # offenders are ranked by severity = below-roofline x time share
    sev = [w["severity"] for w in rep["worst_offenders"]]
    assert sev == sorted(sev, reverse=True)

    # the human rendering covers every section without crashing
    text = roofline.render_perf_report(rep)
    assert "worst offenders" in text and "padding/pruning waste" in text
    assert "serving phase MFU" in text


def test_perf_cli_reproduces_round5_headline_fractions():
    """Acceptance: `obs perf --banked BENCH_BANKED.md` reproduces the
    VERDICT numbers (decode 87.6-90.9% of the v5e HBM roofline, prefill
    MFU 15-28%, MLA ~31-33%) from banked rows with no hand math, and
    the JSON form is schema-stable."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.run(
        [sys.executable, "-m", "flashinfer_tpu.obs", "perf",
         "--banked", "BENCH_BANKED.md", "--json"],
        capture_output=True, text=True, env=env, cwd=REPO_ROOT,
        timeout=300,
    )
    assert p.returncode == 0, p.stderr[-2000:]
    rep = json.loads(p.stdout)
    assert rep["schema"] == "flashinfer_tpu.obs.perf/6"
    assert {"chips", "rows_total", "rows_attributed", "ops",
            "worst_offenders", "waste", "serving_phase_mfu",
            "serving_ici", "scaling_prediction", "serving_disagg",
            "headline"} <= set(rep)
    assert rep["serving_disagg"]["predicted_kv_migrate"][
        "ici_bytes_per_request"] > 0
    assert rep["rows_attributed"] >= 100  # the banked history is deep
    h = rep["headline"]
    dec = h["decode_bs64_ctx4k_pct_roofline"]
    assert 0.86 <= dec["min"] <= 0.89 and 0.89 <= dec["max"] <= 0.92
    mfu = h["prefill_mfu"]
    assert 0.13 <= mfu["min"] <= 0.17 and 0.26 <= mfu["max"] <= 0.30
    mla = h["mla_pct_roofline"]
    assert 0.29 <= mla["min"] <= mla["max"] <= 0.36
    for o in rep["ops"]:  # schema of every table row
        assert {"op", "rows", "bound", "chip", "dtype", "intensity",
                "pct_roofline", "effective_pct_roofline",
                "best_achieved", "time_share"} <= set(o)
        assert o["bound"] in ("memory", "compute")
        assert 0 < o["pct_roofline"]["best"] <= 1.05


def test_perf_cli_exits_nonzero_on_malformed_bank(tmp_path):
    bad = tmp_path / "BAD.md"
    bad.write_text("```json\n{oops\n```\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.run(
        [sys.executable, "-m", "flashinfer_tpu.obs", "perf",
         "--banked", str(bad)],
        capture_output=True, text=True, env=env, cwd=REPO_ROOT,
        timeout=300,
    )
    assert p.returncode == 2
    assert "malformed" in p.stderr


# ---------------------------------------------------------------------------
# coverage + zero-overhead pins
# ---------------------------------------------------------------------------


def test_every_api_op_has_a_costmodel_family():
    """Mirrors analysis L005: a decorated public op with no cost-model
    family would bench but never roofline-attribute.  New @flashinfer_api
    ops must be added to costmodel.API_OP_COSTS (the doctor lists the
    stragglers)."""
    assert costmodel.uncovered_api_ops() == ()
    # every named family is a real formula in the module
    for fam in set(costmodel.API_OP_COSTS.values()):
        assert callable(getattr(costmodel, fam)), fam


def test_zero_overhead_cost_model_never_loads_in_plain_use():
    """Disabled-path pin: with metrics off and no bench/report running,
    plain library use never even imports the cost model or the
    roofline module — zero attribution arithmetic on any hot path."""
    code = (
        "import sys, jax.numpy as jnp\n"
        "import flashinfer_tpu as fi\n"
        "x = jnp.ones((4, 8), jnp.float32)\n"
        "w = jnp.ones((8,), jnp.float32)\n"
        "fi.rmsnorm(x, w)\n"
        "fi.silu_and_mul(jnp.ones((4, 16), jnp.float32))\n"
        "bad = [m for m in sys.modules if m in ("
        "'flashinfer_tpu.obs.costmodel', 'flashinfer_tpu.obs.roofline')]\n"
        "assert not bad, bad\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("FLASHINFER_TPU_METRICS", None)
    p = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, env=env, cwd=REPO_ROOT, timeout=300)
    assert p.returncode == 0, (p.stdout + p.stderr)[-2000:]
