"""Wedge-pattern lint (round-5 verdict item 8): the static checker must
flag each known chip-wedging Mosaic pattern on a deliberately-bad
fixture, honor reasoned suppressions (and reject reasonless ones), pass
the current ops/ tree, and be wired into compile_guard.

The lint lives in ``flashinfer_tpu.analysis.wedge`` (the L004 pass);
the historical ``flashinfer_tpu.wedge_lint`` shim is retired
(docs/migration.md)."""

import os
import textwrap

import pytest

from flashinfer_tpu.analysis import wedge as wedge_lint

BAD_FIXTURE = textwrap.dedent(
    """
    import jax
    import jax.numpy as jnp

    def bad_kernel(q_ref, k_ref, o_ref):
        # W001: 8 heads x 16 pages x 2 parities of literal-unrolled dots
        # = 256 MXU dots — the round-2 wedge shape
        acc = 0.0
        for h in range(8):
            for p in range(16):
                for parity in range(2):
                    acc += jax.lax.dot(q_ref[h], k_ref[p])
        o_ref[0] = acc

    def bad_dma_kernel(x_hbm, buf, sem_ref):
        # W002: 32 unrolled async-copy starts >> DMA queue depth
        for j in range(32):
            pltpu.make_async_copy(x_hbm.at[j], buf.at[j], sem_ref.at[j])

    def bad_repeat_kernel(x_ref, o_ref):
        # W003: lane-dim repeat is an unsupported Mosaic shape cast
        o_ref[...] = jnp.repeat(x_ref[...], 4, axis=-1)

    def bad_dynamic_kernel(x_ref, o_ref, ppc):
        # W004: trace-time unroll with a bound the lint cannot see
        for j in range(ppc):
            o_ref[j] = jax.lax.dot(x_ref[j], x_ref[j])

    def plain_host_helper(x, y):
        # no _ref params, no _kernel suffix: plain host code is exempt
        for j in range(1000):
            x = jnp.repeat(x, 4, axis=-1)
        return x
    """
)


def test_bad_fixture_flags_every_pattern():
    findings = wedge_lint.lint_source(BAD_FIXTURE, "fixture.py")
    codes = {f.code for f in findings}
    assert codes == {"W001", "W002", "W003", "W004"}, findings
    funcs = {f.func for f in findings}
    assert "plain_host_helper" not in funcs


def test_nested_literal_dma_unroll_flagged():
    """W002 must multiply NESTED literal extents: 4 x 4 copies = 16 > 8
    even though each loop alone stays under the queue depth."""
    src = textwrap.dedent(
        """
        def nested_dma_kernel(x_hbm, buf, sem_ref):
            for i in range(4):
                for j in range(4):
                    pltpu.make_async_copy(
                        x_hbm.at[i, j], buf.at[i, j], sem_ref.at[i, j])
        """
    )
    codes = {f.code for f in wedge_lint.lint_source(src, "f.py")}
    assert "W002" in codes


def test_positional_safe_axis_repeat_not_flagged():
    """jnp.repeat(x, 4, 1) — positional sublane axis, the documented
    safe form — must not trip W003."""
    src = textwrap.dedent(
        """
        import jax.numpy as jnp

        def sublane_repeat_kernel(x_ref, o_ref):
            o_ref[...] = jnp.repeat(x_ref[...], 4, 1)

        def lane_repeat_kernel(x_ref, o_ref):
            o_ref[...] = jnp.repeat(x_ref[...], 4, -1)
        """
    )
    findings = wedge_lint.lint_source(src, "f.py")
    assert [f.func for f in findings] == ["lane_repeat_kernel"]


def test_suppression_with_reason_honored():
    src = BAD_FIXTURE.replace(
        "for j in range(32):",
        "for j in range(32):  # wedge-lint: ok on-chip validated "
        "2026-07-29 at this exact config",
    )
    codes = {f.code for f in wedge_lint.lint_source(src, "f.py")}
    assert "W002" not in codes and {"W001", "W003", "W004"} <= codes


def test_reasonless_suppression_is_a_finding():
    src = BAD_FIXTURE.replace(
        "for j in range(32):",
        "for j in range(32):  # wedge-lint: ok",
    )
    findings = wedge_lint.lint_source(src, "f.py")
    codes = {f.code for f in findings}
    assert "W000" in codes and "W002" not in codes


def test_preceding_line_suppression():
    target = "    o_ref[...] = jnp.repeat(x_ref[...], 4, axis=-1)"
    assert target in BAD_FIXTURE  # guard against silent no-op replaces
    src = BAD_FIXTURE.replace(
        target,
        "    # wedge-lint: ok expander-dot verified, kept for "
        "interpret parity\n" + target,
    )
    codes = {f.code for f in wedge_lint.lint_source(src, "f.py")}
    assert "W003" not in codes


def test_ops_tree_is_clean():
    """Every kernel in ops/ either avoids the wedge patterns or carries
    a reasoned suppression — this is the CI gate the verdict asked for."""
    root = os.path.join(os.path.dirname(__file__), "..",
                        "flashinfer_tpu", "ops")
    findings = wedge_lint.lint_tree(os.path.abspath(root))
    assert findings == [], "\n".join(str(f) for f in findings)


def test_compile_guard_wiring(monkeypatch):
    """compile_guard.guarded refuses (strict mode) to first-compile a
    module whose source matches a wedge pattern."""
    import types

    mod = types.ModuleType("fake_bad_kernels")
    mod.__name__ = "fake_bad_kernels_" + str(id(mod))
    from flashinfer_tpu.analysis import wedge as wl

    monkeypatch.setattr(
        wl.inspect, "getsource", lambda m: BAD_FIXTURE, raising=True)
    monkeypatch.setattr(
        wl.inspect, "getsourcefile", lambda m: "fake.py", raising=True)
    monkeypatch.setenv("FLASHINFER_TPU_WEDGE_LINT", "strict")
    with pytest.raises(wl.WedgeLintError, match="W001"):
        wl.check_module(mod)
    # the strict gate re-enforces on EVERY call — a retry must never
    # slip a known-wedging kernel through to a hardware compile
    with pytest.raises(wl.WedgeLintError, match="W001"):
        wl.check_module(mod)
    # warn mode logs but does not raise
    mod2 = types.ModuleType("fake_bad_kernels2")
    mod2.__name__ = "fake_bad_kernels2_" + str(id(mod2))
    monkeypatch.setenv("FLASHINFER_TPU_WEDGE_LINT", "warn")
    findings = wl.check_module(mod2)
    assert {f.code for f in findings} >= {"W001"}
