"""Migration proof #3: mechanical port of the reference test file
``/root/reference/tests/utils/test_sampling.py`` against
``flashinfer_tpu.sampling`` (round-5 verdict item 7, third file — the
sampling surface is a reference headline feature).

Porting deviations, each a written reason:

- **explicit PRNG keys**: the reference samples through torch's stateful
  generator (``generator=`` kwarg); JAX keys are explicit, so every
  sampling call here inserts ``key`` (the documented TPU signature —
  ``jax.random.PRNGKey`` in the second positional slot).  The
  reproducibility tests become key-equality tests, the strongest form
  of the reference's seed/offset checks.
- **trial counts**: the reference loops 1000-5000 stateful draws per
  membership test and 5M draws per frequency test.  Membership
  assertions are PER-DRAW invariants, so 20 split-key draws exercise
  them identically; frequency tests run reduced, chunked trials at
  vocab <= 32000 on CPU CI (the 128k rows and full trial counts run
  under FLASHINFER_TPU_FULL_MATRIX=1 / the hardware tier).
- matrix sampling: collection-time 1/48 stride shared with the other
  ported files; memory gate skips batch*vocab > 2^27 on CPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import flashinfer_tpu as fi
from tests.test_ported_batch_prefill import FULL, _sample

# 2**25: admits (989, 32000) and (99, 128256) but routes (989, 128256)
# to the FULL/hardware tier — its 20-draw full-vocab sort loops exceed
# 9 min per case on CPU
_ELEM_CAP = 2 ** 25


def _mem_gate(batch_size, vocab_size):
    if not FULL and batch_size * vocab_size > _ELEM_CAP:
        pytest.skip(
            f"batch*vocab {batch_size * vocab_size:.1e} exceeds the CPU "
            f"CI cap {_ELEM_CAP:.1e}; FLASHINFER_TPU_FULL_MATRIX run")


def normal_distribution(std):
    def normal_noise(shape, key):
        return jax.random.normal(key, shape) * std

    normal_noise.__name__ = f"normal_distribution(std={std})"
    return normal_noise


def gumbel_distribution(beta):
    def gumbel_noise(shape, key):
        U = jax.random.uniform(key, shape)
        eps = 1e-20
        return jnp.log(-jnp.log(U + eps) + eps) / beta

    gumbel_noise.__name__ = f"gumbel_distribution(beta={beta})"
    return gumbel_noise


_DISTS = [normal_distribution(1), normal_distribution(5),
          gumbel_distribution(0.1)]


def _norm_probs(batch_size, vocab_size, seed):
    pre = jax.random.uniform(jax.random.PRNGKey(seed),
                             (batch_size, vocab_size))
    return pre / pre.sum(-1, keepdims=True)


@pytest.mark.parametrize(
    "batch_size,vocab_size,distribution,temperature,temperature_arr,"
    "neg_inf_input",
    _sample("softmax", [1, 99, 989], [111, 32000, 128256], _DISTS,
            [1.0, 0.5, 0.1], [True, False], [True, False],
            specials=[(5, True)]),
)
def test_softmax(batch_size, vocab_size, distribution, temperature,
                 temperature_arr, neg_inf_input):
    """Reference test_softmax (test_sampling.py:41-76)."""
    _mem_gate(batch_size, vocab_size)
    keys = jax.random.split(jax.random.PRNGKey(42), 3)
    logits = distribution((batch_size, vocab_size), keys[0])
    if neg_inf_input:
        n = batch_size * vocab_size
        num_inf = int(jax.random.randint(keys[1], (), 0, n - 1))
        inf_idx = jax.random.permutation(keys[2], n)[:num_inf]
        logits = logits.reshape(-1).at[inf_idx].set(-jnp.inf).reshape(
            batch_size, vocab_size)
    if temperature_arr:
        t = jnp.full((batch_size,), temperature)
        probs = fi.sampling.softmax(logits, temperature=t)
        logits_scaled = logits / t[:, None]
    else:
        probs = fi.sampling.softmax(logits, temperature=temperature)
        logits_scaled = logits / temperature
    probs_ref = jax.nn.softmax(logits_scaled.astype(jnp.float32), axis=-1)
    np.testing.assert_allclose(np.asarray(probs), np.asarray(probs_ref),
                               atol=1e-5)


@pytest.mark.parametrize(
    "vocab_size,distribution,zero_ratio",
    _sample("freq", [111, 32000, 128256], _DISTS, [0.0, 0.5, 0.9],
            specials=[(2, 0.9)]),
)
def test_sampling_freq(vocab_size, distribution, zero_ratio):
    """Reference test_sampling_freq (test_sampling.py:79-106): empirical
    frequency tracks the distribution; -inf rows never sampled.

    CPU CI runs vocab=111 only: cosine similarity of an empirical
    histogram needs trials >> vocab / E[p^2] to clear 0.98 for FLAT
    distributions — at vocab 32000+ that is millions of draws (the
    reference uses 5M), which the FULL/hardware run performs."""
    if not FULL and vocab_size > 111:
        pytest.skip(
            "frequency similarity at vocab > 111 needs millions of "
            "trials to converge for flat distributions; the "
            "FLASHINFER_TPU_FULL_MATRIX/hardware run uses the "
            "reference's 5M trials")
    keys = jax.random.split(jax.random.PRNGKey(42), 3)
    logits = distribution((1, vocab_size), keys[0])
    zero_idx = np.asarray(
        jax.random.permutation(keys[1], vocab_size)
    )[: int(vocab_size * zero_ratio)]
    logits = logits.at[:, zero_idx].set(-jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1)

    # FULL: the reference's 5M trials; CPU CI: 49k trials at vocab 111
    chunk = 2048
    n_chunks = -(-5_000_000 // chunk) if FULL else 24
    counter = np.zeros(vocab_size, np.int64)
    idx = jnp.zeros((chunk,), jnp.int32)
    for i, k in enumerate(jax.random.split(keys[2], n_chunks)):
        samples = fi.sampling.sampling_from_probs(probs, k, indices=idx)
        counter += np.bincount(np.asarray(samples), minlength=vocab_size)
    num_trials = chunk * n_chunks
    freq = counter.astype(np.float64) / num_trials
    assert counter[zero_idx].sum() == 0
    p = np.asarray(probs[0], np.float64)
    similarity = (freq @ p) / (np.linalg.norm(freq) * np.linalg.norm(p))
    assert similarity > 0.98, f"similarity: {similarity}"


@pytest.mark.parametrize(
    "batch_size,vocab_size",
    _sample("bounds", [1, 99, 989], [111, 32000, 128256]),
)
def test_sampling(batch_size, vocab_size):
    """Reference test_sampling (test_sampling.py:179-190): 20 split-key
    draws replace 5000 stateful draws (per-draw invariant)."""
    _mem_gate(batch_size, vocab_size)
    probs = _norm_probs(batch_size, vocab_size, 42)
    for k in jax.random.split(jax.random.PRNGKey(0), 20):
        samples = fi.sampling.sampling_from_probs(probs, k)
        s = np.asarray(samples)
        assert (s < vocab_size).all() and (s >= 0).all()


@pytest.mark.parametrize(
    "batch_size,vocab_size",
    _sample("bounds_logits", [1, 99, 989], [111, 32000, 128256]),
)
def test_sampling_from_logits(batch_size, vocab_size):
    """Reference test_sampling_from_logits (test_sampling.py:192-201)."""
    _mem_gate(batch_size, vocab_size)
    logits = jax.random.normal(jax.random.PRNGKey(42),
                               (batch_size, vocab_size))
    for k in jax.random.split(jax.random.PRNGKey(0), 20):
        s = np.asarray(fi.sampling.sampling_from_logits(logits, k))
        assert (s < vocab_size).all() and (s >= 0).all()


@pytest.mark.parametrize(
    "batch_size,vocab_size,p",
    _sample("top_p", [1, 99, 989], [111, 32000, 128256],
            [0.1, 0.5, 0.9]),
)
def test_top_p_sampling(batch_size, vocab_size, p):
    """Reference test_top_p_sampling (test_sampling.py:227-244): every
    sample lies in the top-p nucleus."""
    _mem_gate(batch_size, vocab_size)
    probs = _norm_probs(batch_size, vocab_size, 42)
    pn = np.asarray(probs, np.float64)
    order = np.argsort(pn, axis=-1)
    sp = np.take_along_axis(pn, order, -1)
    cdf = np.cumsum(sp, -1)
    mask = np.zeros_like(pn, np.int32)
    # 1e-4 band: the implementation's f32 cumsum at 128k vocab carries
    # ~1e-5..1e-4 of mass error vs this f64 oracle (same tolerance the
    # reference's joint test uses)
    np.put_along_axis(mask, order, (cdf > (1 - p) - 1e-4).astype(np.int32),
                      -1)
    for k in jax.random.split(jax.random.PRNGKey(0), 20):
        s = np.asarray(fi.sampling.top_p_sampling_from_probs(probs, k, p))
        assert (mask[np.arange(batch_size), s] == 1).all()


@pytest.mark.parametrize(
    "batch_size,vocab_size,k",
    _sample("top_k", [1, 99, 989], [111, 32000, 128256],
            [10, 100, 500]),
)
def test_top_k_sampling(batch_size, vocab_size, k):
    """Reference test_top_k_sampling (test_sampling.py:247-266)."""
    if k > vocab_size:
        pytest.skip("k should be less than vocab_size")
    _mem_gate(batch_size, vocab_size)
    probs = _norm_probs(batch_size, vocab_size, 42)
    pn = np.asarray(probs, np.float64)
    pivot = np.sort(pn, -1)[:, ::-1][:, k - 1]
    mask = (pn >= pivot[:, None]).astype(np.int32)
    for kk in jax.random.split(jax.random.PRNGKey(0), 20):
        s = np.asarray(fi.sampling.top_k_sampling_from_probs(probs, kk, k))
        assert (mask[np.arange(batch_size), s] == 1).all()


@pytest.mark.parametrize(
    "batch_size,vocab_size,k",
    _sample("top_k_var", [1, 99, 989], [111, 32000, 128256],
            [10, 100, 500]),
)
def test_top_k_sampling_with_variable_k(batch_size, vocab_size, k):
    """Reference variable-k variant (test_sampling.py:269-289): per-row
    k array."""
    if k > vocab_size:
        pytest.skip("k should be less than vocab_size")
    _mem_gate(batch_size, vocab_size)
    probs = _norm_probs(batch_size, vocab_size, 42)
    karr = jax.random.randint(jax.random.PRNGKey(1), (batch_size,), 1,
                              k + 1)
    pn = np.asarray(probs, np.float64)
    sp = np.sort(pn, -1)[:, ::-1]
    pivot = sp[np.arange(batch_size), np.asarray(karr) - 1]
    mask = (pn >= pivot[:, None]).astype(np.int32)
    for kk in jax.random.split(jax.random.PRNGKey(0), 20):
        s = np.asarray(
            fi.sampling.top_k_sampling_from_probs(probs, kk, karr))
        assert (s < vocab_size).all() and (s >= 0).all()
        assert (mask[np.arange(batch_size), s] == 1).all()


@pytest.mark.parametrize(
    "batch_size,vocab_size,p",
    _sample("min_p", [1, 99, 989], [111, 32000, 128256],
            [0.05, 0.1, 0.2, 0.7, 1]),
)
def test_min_p_sampling(batch_size, vocab_size, p):
    """Reference test_min_p_sampling (test_sampling.py:292-318)."""
    _mem_gate(batch_size, vocab_size)
    probs = _norm_probs(batch_size, vocab_size, 42)
    pn = np.asarray(probs, np.float64)
    top = pn.max(-1, keepdims=True)
    mask = (pn >= p * top).astype(np.int32)
    min_p = jnp.full((batch_size,), float(p))
    for kk in jax.random.split(jax.random.PRNGKey(0), 20):
        s = np.asarray(
            fi.sampling.min_p_sampling_from_probs(probs, kk, min_p))
        assert (mask[np.arange(batch_size), s] == 1).all()


@pytest.mark.parametrize(
    "batch_size,vocab_size,p",
    _sample("joint", [1, 99, 989], [111, 32000, 128256], [0.1, 0.5]),
)
def test_top_k_top_p_joint_sampling_from_probs(batch_size, vocab_size, p):
    """Reference joint filter test (test_sampling.py:323-360)."""
    _mem_gate(batch_size, vocab_size)
    k = int(vocab_size * 0.5) if p == 0.1 else int(vocab_size * 0.1)
    probs = _norm_probs(batch_size, vocab_size, 42)
    pn = np.asarray(probs, np.float64)
    order = np.argsort(pn, -1)
    sp = np.take_along_axis(pn, order, -1)
    cdf = np.cumsum(sp, -1)
    mask_p = np.zeros_like(pn, np.int32)
    np.put_along_axis(mask_p, order,
                      (cdf > (1 - p) - 1e-4).astype(np.int32), -1)
    pivot = np.sort(pn, -1)[:, ::-1][:, k - 1]
    mask_k = (pn >= pivot[:, None]).astype(np.int32)
    mask = np.minimum(mask_p, mask_k)
    tp = jnp.full((batch_size,), float(p))
    tk = jnp.full((batch_size,), k, jnp.int32)
    for kk in jax.random.split(jax.random.PRNGKey(0), 20):
        s = np.asarray(fi.sampling.top_k_top_p_sampling_from_probs(
            probs, kk, tk, tp, filter_apply_order="joint"))
        assert (s < vocab_size).all() and (s >= 0).all()
        assert (mask[np.arange(batch_size), s] == 1).all()


@pytest.mark.parametrize(
    "batch_size,vocab_size,p",
    _sample("joint_logits", [1, 99, 989], [111, 32000, 128256],
            [0.1, 0.5]),
)
def test_top_k_top_p_joint_sampling_from_logits(batch_size, vocab_size, p):
    """Reference alignment test (test_sampling.py:399-425): from_logits
    with a given key must equal softmax + from_probs with the SAME key
    (the reference's cloned-generator check, exact here)."""
    _mem_gate(batch_size, vocab_size)
    k = int(vocab_size * 0.5) if p == 0.1 else int(vocab_size * 0.1)
    logits = jax.random.uniform(jax.random.PRNGKey(42),
                                (batch_size, vocab_size)) * 5
    key = jax.random.PRNGKey(7)
    s1 = fi.sampling.top_k_top_p_sampling_from_logits(
        logits, key, k, p, filter_apply_order="joint")
    s2 = fi.sampling.top_k_top_p_sampling_from_probs(
        jax.nn.softmax(logits, axis=-1), key, k, p,
        filter_apply_order="joint")
    assert (np.asarray(s1) == np.asarray(s2)).all()


@pytest.mark.parametrize(
    "batch_size,vocab_size,p",
    _sample("renorm_p", [1, 99, 989], [111, 32000, 128256],
            [0.1, 0.5, 0.9, 1.0]),
)
def test_top_p_renorm_probs(batch_size, vocab_size, p):
    """Reference test_top_p_renorm_probs (test_sampling.py:428-450)."""
    _mem_gate(batch_size, vocab_size)
    probs = _norm_probs(batch_size, vocab_size, 42)
    pn = np.asarray(probs, np.float64)
    order = np.argsort(pn, -1)
    sp = np.take_along_axis(pn, order, -1)
    cdf = np.cumsum(sp, -1)
    mask = np.zeros_like(pn, np.int32)
    np.put_along_axis(mask, order, (cdf >= (1 - p) - 1e-9).astype(np.int32),
                      -1)
    ref = np.where(mask == 1, pn, 0.0)
    ref = ref / ref.sum(-1, keepdims=True)
    out = np.asarray(fi.sampling.top_p_renorm_probs(probs, p), np.float64)
    np.testing.assert_allclose(out, ref, rtol=1e-3, atol=1e-3)


@pytest.mark.parametrize(
    "batch_size,vocab_size,k",
    _sample("renorm_k", [1, 99, 989], [111, 32000, 128256],
            [10, 100, 500]),
)
def test_top_k_renorm_probs(batch_size, vocab_size, k):
    """Reference test_top_k_renorm_probs (test_sampling.py:493+)."""
    if k > vocab_size:
        pytest.skip("k should be less than vocab_size")
    _mem_gate(batch_size, vocab_size)
    probs = _norm_probs(batch_size, vocab_size, 42)
    pn = np.asarray(probs, np.float64)
    pivot = np.sort(pn, -1)[:, ::-1][:, k - 1]
    ref = np.where(pn >= pivot[:, None], pn, 0.0)
    ref = ref / ref.sum(-1, keepdims=True)
    out = np.asarray(fi.sampling.top_k_renorm_probs(probs, k), np.float64)
    np.testing.assert_allclose(out, ref, rtol=1e-3, atol=1e-3)


@pytest.mark.parametrize(
    "batch_size,vocab_size",
    _sample("repro", [1, 99, 989], [111, 32000, 128256]),
)
def test_sampling_seed_reproducibility(batch_size, vocab_size):
    """Reference seed/offset reproducibility tests
    (test_sampling.py:981-1062), in their exact-key JAX form: same key
    -> identical samples, different keys -> (overwhelmingly) different."""
    _mem_gate(batch_size, vocab_size)
    probs = _norm_probs(batch_size, vocab_size, 42)
    key = jax.random.PRNGKey(3)
    a = np.asarray(fi.sampling.sampling_from_probs(probs, key))
    b = np.asarray(fi.sampling.sampling_from_probs(probs, key))
    assert (a == b).all()
    logits = jnp.log(jnp.maximum(probs, 1e-30))
    la = np.asarray(fi.sampling.sampling_from_logits(logits, key))
    lb = np.asarray(fi.sampling.sampling_from_logits(logits, key))
    assert (la == lb).all()
    if vocab_size > 1000 and batch_size > 1:
        c = np.asarray(
            fi.sampling.sampling_from_probs(probs, jax.random.PRNGKey(4)))
        assert (a != c).any()


def test_chain_speculative_sampling_port():
    """Reference test_chain_speculative_sampling (test_sampling.py:773):
    rejection-based verify — accepted prefix tokens must match greedy
    membership in the draft distribution's support, and output length is
    num_spec + 1 with -1 padding after the first bonus token."""
    B, L, V = 4, 3, 64
    keys = jax.random.split(jax.random.PRNGKey(0), 3)
    draft_probs = jax.nn.softmax(
        jax.random.normal(keys[0], (B, L, V)) * 2, -1)
    draft_ids = jnp.argmax(draft_probs, -1).astype(jnp.int32)
    target_probs = jax.nn.softmax(
        jax.random.normal(keys[1], (B, L + 1, V)) * 2, -1)
    out, accepted, emitted = fi.sampling.chain_speculative_sampling(
        draft_probs, draft_ids, target_probs, keys[2])
    o = np.asarray(out)
    assert o.shape == (B, L + 1)
    acc = np.asarray(accepted)
    emt = np.asarray(emitted)
    for b in range(B):
        # emitted = leading accepted run (tokens actually kept);
        # accepted = per-position telemetry count, >= emitted
        n = int(emt[b])
        assert 0 <= n <= L and acc[b] >= n
        # emitted draft tokens + one bonus/resampled token, then -1 pad
        assert (o[b, : n + 1] >= 0).all()
        assert (o[b, n + 1:] == -1).all()
        # the emitted prefix is exactly the draft tokens
        assert (o[b, :n] == np.asarray(draft_ids)[b, :n]).all()
