"""Real-TPU correctness tier at serving shapes (Llama-3-8B geometry:
32 q heads / 8 kv heads, head_dim 128, ctx 4k, bf16).

The smoke tier (test_tpu_smoke.py) proves each kernel Mosaic-compiles;
this tier is the TPU analogue of the reference's GPU-correctness tests
(tests/attention/test_batch_prefill_kernels.py): oracle comparison at the
shapes the benchmarks run.  Auto-skips off-TPU.  Run each test in its own
process under a timeout — a Mosaic hang must cost one slot, not the chip.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import flashinfer_tpu as fi
from flashinfer_tpu.testing import attention_ref

pytestmark = pytest.mark.tpu_only

HQ, HKV, D = 32, 8, 128
BF16_TOL = dict(rtol=3e-2, atol=3e-2)


def test_flash_ragged_prefill_llama_shape():
    from flashinfer_tpu.ops import flash_attention

    T = 4096
    q = jax.random.normal(jax.random.PRNGKey(0), (T, HQ, D), jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(1), (T, HKV, D), jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(2), (T, HKV, D), jnp.bfloat16)
    seg = jnp.zeros((T,), jnp.int32)
    pos = jnp.arange(T)
    out = flash_attention(
        q, k, v, seg, seg, pos, pos, causal=True, sm_scale=D ** -0.5
    )
    ref = attention_ref(q, k, v, causal=True, sm_scale=D ** -0.5)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), **BF16_TOL
    )


def test_paged_decode_llama_shape():
    from flashinfer_tpu.ops import paged_decode_attention, xla_paged_decode

    B, PS, ctx = 16, 16, 4096
    ppr = ctx // PS
    npages = B * ppr
    pt = jnp.asarray(
        np.random.default_rng(0).permutation(npages).astype(np.int32)
    ).reshape(B, ppr)
    lens = jnp.asarray(
        np.random.default_rng(1).integers(1, ctx + 1, B).astype(np.int32)
    )
    kc = jax.random.normal(
        jax.random.PRNGKey(0), (npages, HKV, PS, D), jnp.bfloat16
    )
    vc = jax.random.normal(
        jax.random.PRNGKey(1), (npages, HKV, PS, D), jnp.bfloat16
    )
    q = jax.random.normal(jax.random.PRNGKey(2), (B, HQ, D), jnp.bfloat16)
    o = paged_decode_attention(
        q, kc, vc, pt, lens, sm_scale=D ** -0.5, kv_layout="HND"
    )
    ref = xla_paged_decode(
        q, jnp.swapaxes(kc, 1, 2), jnp.swapaxes(vc, 1, 2), pt, lens,
        sm_scale=D ** -0.5,
    )
    np.testing.assert_allclose(
        np.asarray(o, np.float32), np.asarray(ref, np.float32), **BF16_TOL
    )


def test_fused_paged_prefill_llama_shape():
    """First-class hardware check of the work-unit fused prefill kernel
    (ops/paged_prefill.py) against the gather+flash path, mixed ragged
    batch with append semantics."""
    from flashinfer_tpu.ops.paged_prefill import (
        build_prefill_work_units, fused_paged_prefill,
    )

    PS = 16
    rng = np.random.default_rng(0)
    qo_lens = [512, 128, 1024, 37]
    kv_lens = [1024, 512, 2048, 333]
    qo_indptr = np.concatenate([[0], np.cumsum(qo_lens)]).astype(np.int32)
    pages_per = [int(np.ceil(l / PS)) for l in kv_lens]
    kv_page_indptr = np.concatenate([[0], np.cumsum(pages_per)]).astype(np.int32)
    npages = int(kv_page_indptr[-1])
    kv_page_indices = rng.permutation(npages).astype(np.int32)

    total_q = int(qo_indptr[-1])
    q = jax.random.normal(jax.random.PRNGKey(0), (total_q, HQ, D), jnp.bfloat16)
    kc = jax.random.normal(
        jax.random.PRNGKey(1), (npages, HKV, PS, D), jnp.bfloat16
    )
    vc = jax.random.normal(
        jax.random.PRNGKey(2), (npages, HKV, PS, D), jnp.bfloat16
    )

    plan_np = build_prefill_work_units(
        qo_indptr, kv_page_indptr, kv_page_indices,
        np.asarray(kv_lens, np.int32), block_q=128, pages_per_chunk=8,
        page_size=PS,
    )
    num_units = plan_np.pop("num_units")
    plan_np.pop("block_q"), plan_np.pop("pages_per_chunk")
    plan = {k: jnp.asarray(v) for k, v in plan_np.items()}
    out = fused_paged_prefill(
        q, kc, vc, plan, num_units=num_units, block_q=128, pages_per_chunk=8,
        sm_scale=D ** -0.5, causal=True,
    )

    # oracle: per-request dense attention with append (bottom-right) causal
    for r in range(len(qo_lens)):
        qs, qe = int(qo_indptr[r]), int(qo_indptr[r + 1])
        pages = kv_page_indices[kv_page_indptr[r]:kv_page_indptr[r + 1]]
        kr = np.asarray(kc, np.float32)[pages]  # [p, HKV, PS, D]
        vr = np.asarray(vc, np.float32)[pages]
        kr = kr.transpose(0, 2, 1, 3).reshape(-1, HKV, D)[: kv_lens[r]]
        vr = vr.transpose(0, 2, 1, 3).reshape(-1, HKV, D)[: kv_lens[r]]
        qr = np.asarray(q, np.float32)[qs:qe]
        qpos = kv_lens[r] - qo_lens[r] + np.arange(qo_lens[r])
        kpos = np.arange(kv_lens[r])
        kg = np.repeat(kr, HQ // HKV, axis=1)
        vg = np.repeat(vr, HQ // HKV, axis=1)
        s = np.einsum("qhd,khd->hqk", qr, kg) * (D ** -0.5)
        s = np.where(kpos[None, None, :] <= qpos[None, :, None], s, -1e30)
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        ref_r = np.einsum("hqk,khd->qhd", p, vg)
        np.testing.assert_allclose(
            np.asarray(out, np.float32)[qs:qe], ref_r, **BF16_TOL
        )


def test_mla_decode_deepseek_shape():
    from flashinfer_tpu.ops.mla_decode import (
        mla_paged_decode_attention, xla_mla_paged_decode,
    )

    B, H, d_ckv, d_kpe, PS, ctx = 4, 128, 512, 64, 16, 2048
    ppr = ctx // PS
    npages = B * ppr
    ckv = jax.random.normal(
        jax.random.PRNGKey(0), (npages, PS, d_ckv), jnp.bfloat16
    )
    kpe = jax.random.normal(
        jax.random.PRNGKey(1), (npages, PS, d_kpe), jnp.bfloat16
    )
    qn = jax.random.normal(jax.random.PRNGKey(2), (B, H, d_ckv), jnp.bfloat16)
    qp = jax.random.normal(jax.random.PRNGKey(3), (B, H, d_kpe), jnp.bfloat16)
    pt = jnp.arange(npages, dtype=jnp.int32).reshape(B, ppr)
    lens = jnp.array([2048, 1031, 64, 1999], jnp.int32)
    sm = (d_ckv + d_kpe) ** -0.5
    o = mla_paged_decode_attention(qn, qp, ckv, kpe, pt, lens, sm_scale=sm)
    ref = xla_mla_paged_decode(qn, qp, ckv, kpe, pt, lens, sm_scale=sm)
    np.testing.assert_allclose(
        np.asarray(o, np.float32), np.asarray(ref, np.float32),
        rtol=5e-2, atol=5e-2,
    )


def test_rmsnorm_llama_shape():
    T, H = 4096, 4096
    x = jax.random.normal(jax.random.PRNGKey(0), (T, H), jnp.bfloat16)
    w = jax.random.normal(jax.random.PRNGKey(1), (H,), jnp.bfloat16)
    out = fi.rmsnorm(x, w)
    xf = np.asarray(x, np.float32)
    ref = xf / np.sqrt((xf ** 2).mean(-1, keepdims=True) + 1e-6)
    ref = ref * np.asarray(w, np.float32)
    np.testing.assert_allclose(np.asarray(out, np.float32), ref, **BF16_TOL)
