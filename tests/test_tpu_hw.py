"""Real-TPU correctness tier at serving shapes (Llama-3-8B geometry:
32 q heads / 8 kv heads, head_dim 128, ctx 4k, bf16).

The smoke tier (test_tpu_smoke.py) proves each kernel Mosaic-compiles;
this tier is the TPU analogue of the reference's GPU-correctness tests
(tests/attention/test_batch_prefill_kernels.py): oracle comparison at the
shapes the benchmarks run.  Auto-skips off-TPU.  Run each test in its own
process under a timeout — a Mosaic hang must cost one slot, not the chip.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import flashinfer_tpu as fi
from flashinfer_tpu.testing import attention_ref

pytestmark = pytest.mark.tpu_only

HQ, HKV, D = 32, 8, 128
BF16_TOL = dict(rtol=3e-2, atol=3e-2)


def test_flash_ragged_prefill_llama_shape():
    from flashinfer_tpu.ops import flash_attention

    T = 4096
    q = jax.random.normal(jax.random.PRNGKey(0), (T, HQ, D), jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(1), (T, HKV, D), jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(2), (T, HKV, D), jnp.bfloat16)
    seg = jnp.zeros((T,), jnp.int32)
    pos = jnp.arange(T)
    out = flash_attention(
        q, k, v, seg, seg, pos, pos, causal=True, sm_scale=D ** -0.5
    )
    ref = attention_ref(q, k, v, causal=True, sm_scale=D ** -0.5)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), **BF16_TOL
    )


def test_paged_decode_llama_shape():
    from flashinfer_tpu.ops import paged_decode_attention, xla_paged_decode

    B, PS, ctx = 16, 16, 4096
    ppr = ctx // PS
    npages = B * ppr
    pt = jnp.asarray(
        np.random.default_rng(0).permutation(npages).astype(np.int32)
    ).reshape(B, ppr)
    lens = jnp.asarray(
        np.random.default_rng(1).integers(1, ctx + 1, B).astype(np.int32)
    )
    kc = jax.random.normal(
        jax.random.PRNGKey(0), (npages, HKV, PS, D), jnp.bfloat16
    )
    vc = jax.random.normal(
        jax.random.PRNGKey(1), (npages, HKV, PS, D), jnp.bfloat16
    )
    q = jax.random.normal(jax.random.PRNGKey(2), (B, HQ, D), jnp.bfloat16)
    o = paged_decode_attention(
        q, kc, vc, pt, lens, sm_scale=D ** -0.5, kv_layout="HND"
    )
    ref = xla_paged_decode(
        q, jnp.swapaxes(kc, 1, 2), jnp.swapaxes(vc, 1, 2), pt, lens,
        sm_scale=D ** -0.5,
    )
    np.testing.assert_allclose(
        np.asarray(o, np.float32), np.asarray(ref, np.float32), **BF16_TOL
    )


def test_paged_decode_static_prefetch_on_chip():
    """The static-parity next-request prefetch (default tactic since
    2026-07-31) must be BIT-IDENTICAL to the plain kernel on hardware —
    mixed even/odd/zero chunk counts exercise the scalar-derived
    warmup/epilogue DMA handshake."""
    from flashinfer_tpu.ops import paged_decode_attention

    B, PS, ctx = 16, 16, 4096
    ppr = ctx // PS
    npages = B * ppr
    pt = jnp.asarray(
        np.random.default_rng(0).permutation(npages).astype(np.int32)
    ).reshape(B, ppr)
    lens_np = np.random.default_rng(1).integers(0, ctx + 1, B)
    lens_np[0] = ctx  # even chunk count at the tuned ppc
    lens_np[1] = 0    # zero-length request mid-batch
    lens = jnp.asarray(lens_np.astype(np.int32))
    kc = jax.random.normal(
        jax.random.PRNGKey(0), (npages, HKV, PS, D), jnp.bfloat16
    )
    vc = jax.random.normal(
        jax.random.PRNGKey(1), (npages, HKV, PS, D), jnp.bfloat16
    )
    q = jax.random.normal(jax.random.PRNGKey(2), (B, HQ, D), jnp.bfloat16)
    outs = {}
    for mode in (False, "static"):
        outs[mode] = paged_decode_attention(
            q, kc, vc, pt, lens, sm_scale=D ** -0.5, kv_layout="HND",
            pages_per_chunk=16, cross_step_prefetch=mode,
        )
    np.testing.assert_array_equal(
        np.asarray(outs[False], np.float32),
        np.asarray(outs["static"], np.float32),
    )


def test_fused_paged_prefill_llama_shape():
    """First-class hardware check of the work-unit fused prefill kernel
    (ops/paged_prefill.py) against the gather+flash path, mixed ragged
    batch with append semantics."""
    from flashinfer_tpu.ops.paged_prefill import (
        build_prefill_work_units, fused_paged_prefill,
    )

    PS = 16
    rng = np.random.default_rng(0)
    qo_lens = [512, 128, 1024, 37]
    kv_lens = [1024, 512, 2048, 333]
    qo_indptr = np.concatenate([[0], np.cumsum(qo_lens)]).astype(np.int32)
    pages_per = [int(np.ceil(l / PS)) for l in kv_lens]
    kv_page_indptr = np.concatenate([[0], np.cumsum(pages_per)]).astype(np.int32)
    npages = int(kv_page_indptr[-1])
    kv_page_indices = rng.permutation(npages).astype(np.int32)

    total_q = int(qo_indptr[-1])
    q = jax.random.normal(jax.random.PRNGKey(0), (total_q, HQ, D), jnp.bfloat16)
    kc = jax.random.normal(
        jax.random.PRNGKey(1), (npages, HKV, PS, D), jnp.bfloat16
    )
    vc = jax.random.normal(
        jax.random.PRNGKey(2), (npages, HKV, PS, D), jnp.bfloat16
    )

    plan_np = build_prefill_work_units(
        qo_indptr, kv_page_indptr, kv_page_indices,
        np.asarray(kv_lens, np.int32), block_q=128, pages_per_chunk=8,
        page_size=PS,
    )
    num_units = plan_np.pop("num_units")
    plan_np.pop("block_q"), plan_np.pop("pages_per_chunk")
    plan_np.pop("stats")
    plan = {k: jnp.asarray(v) for k, v in plan_np.items()}
    out = fused_paged_prefill(
        q, kc, vc, plan, num_units=num_units, block_q=128, pages_per_chunk=8,
        sm_scale=D ** -0.5, causal=True,
    )

    # oracle: per-request dense attention with append (bottom-right) causal
    for r in range(len(qo_lens)):
        qs, qe = int(qo_indptr[r]), int(qo_indptr[r + 1])
        pages = kv_page_indices[kv_page_indptr[r]:kv_page_indptr[r + 1]]
        kr = np.asarray(kc, np.float32)[pages]  # [p, HKV, PS, D]
        vr = np.asarray(vc, np.float32)[pages]
        kr = kr.transpose(0, 2, 1, 3).reshape(-1, HKV, D)[: kv_lens[r]]
        vr = vr.transpose(0, 2, 1, 3).reshape(-1, HKV, D)[: kv_lens[r]]
        qr = np.asarray(q, np.float32)[qs:qe]
        qpos = kv_lens[r] - qo_lens[r] + np.arange(qo_lens[r])
        kpos = np.arange(kv_lens[r])
        kg = np.repeat(kr, HQ // HKV, axis=1)
        vg = np.repeat(vr, HQ // HKV, axis=1)
        s = np.einsum("qhd,khd->hqk", qr, kg) * (D ** -0.5)
        s = np.where(kpos[None, None, :] <= qpos[None, :, None], s, -1e30)
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        ref_r = np.einsum("hqk,khd->qhd", p, vg)
        np.testing.assert_allclose(
            np.asarray(out, np.float32)[qs:qe], ref_r, **BF16_TOL
        )


def test_mla_decode_deepseek_shape():
    from flashinfer_tpu.ops.mla_decode import (
        mla_paged_decode_attention, xla_mla_paged_decode,
    )

    B, H, d_ckv, d_kpe, PS, ctx = 4, 128, 512, 64, 16, 2048
    ppr = ctx // PS
    npages = B * ppr
    ckv = jax.random.normal(
        jax.random.PRNGKey(0), (npages, PS, d_ckv), jnp.bfloat16
    )
    kpe = jax.random.normal(
        jax.random.PRNGKey(1), (npages, PS, d_kpe), jnp.bfloat16
    )
    qn = jax.random.normal(jax.random.PRNGKey(2), (B, H, d_ckv), jnp.bfloat16)
    qp = jax.random.normal(jax.random.PRNGKey(3), (B, H, d_kpe), jnp.bfloat16)
    pt = jnp.arange(npages, dtype=jnp.int32).reshape(B, ppr)
    lens = jnp.array([2048, 1031, 64, 1999], jnp.int32)
    sm = (d_ckv + d_kpe) ** -0.5
    o = mla_paged_decode_attention(qn, qp, ckv, kpe, pt, lens, sm_scale=sm)
    ref = xla_mla_paged_decode(qn, qp, ckv, kpe, pt, lens, sm_scale=sm)
    np.testing.assert_allclose(
        np.asarray(o, np.float32), np.asarray(ref, np.float32),
        rtol=5e-2, atol=5e-2,
    )


def test_mla_decode_packed_layout_on_chip():
    """Packed single-buffer MLA scratch (one concatenated score dot,
    128-aligned dst lane slices 0:512 / 512:640) vs the validated split
    kernel on real hardware — first Mosaic compile of the packed form."""
    from flashinfer_tpu.ops.mla_decode import mla_paged_decode_attention

    B, H, d_ckv, d_kpe, PS, ctx = 4, 128, 512, 64, 16, 2048
    ppr = ctx // PS
    npages = B * ppr
    ckv = jax.random.normal(
        jax.random.PRNGKey(0), (npages, PS, d_ckv), jnp.bfloat16
    )
    kpe = jax.random.normal(
        jax.random.PRNGKey(1), (npages, PS, d_kpe), jnp.bfloat16
    )
    qn = jax.random.normal(jax.random.PRNGKey(2), (B, H, d_ckv), jnp.bfloat16)
    qp = jax.random.normal(jax.random.PRNGKey(3), (B, H, d_kpe), jnp.bfloat16)
    pt = jnp.arange(npages, dtype=jnp.int32).reshape(B, ppr)
    lens = jnp.array([2048, 1031, 64, 1999], jnp.int32)
    sm = (d_ckv + d_kpe) ** -0.5
    o_p = mla_paged_decode_attention(
        qn, qp, ckv, kpe, pt, lens, sm_scale=sm, layout="packed")
    o_s = mla_paged_decode_attention(
        qn, qp, ckv, kpe, pt, lens, sm_scale=sm, layout="split")
    np.testing.assert_allclose(
        np.asarray(o_p, np.float32), np.asarray(o_s, np.float32),
        rtol=2e-2, atol=2e-2,
    )


def test_rmsnorm_llama_shape():
    T, H = 4096, 4096
    x = jax.random.normal(jax.random.PRNGKey(0), (T, H), jnp.bfloat16)
    w = jax.random.normal(jax.random.PRNGKey(1), (H,), jnp.bfloat16)
    out = fi.rmsnorm(x, w)
    xf = np.asarray(x, np.float32)
    ref = xf / np.sqrt((xf ** 2).mean(-1, keepdims=True) + 1e-6)
    ref = ref * np.asarray(w, np.float32)
    np.testing.assert_allclose(np.asarray(out, np.float32), ref, **BF16_TOL)


def test_sampling_threshold_kernel_128k_vocab():
    """VMEM bisection top-k/top-p filter vs the XLA sort filter at the
    serving vocab (128k) — kept-set mass must agree."""
    from flashinfer_tpu.ops.sampling_kernels import threshold_select
    from flashinfer_tpu.sampling import _top_k_top_p_filter_xla

    bs, vocab = 16, 128 * 1024
    logits = jax.random.normal(jax.random.PRNGKey(0), (bs, vocab)) * 4.0
    probs = jax.nn.softmax(logits, axis=-1)
    k = jnp.full((bs,), 40.0)
    tp = jnp.full((bs,), 0.95)
    got = np.asarray(threshold_select(probs, k, tp, mode="top_k_top_p_seq"))
    ref = np.asarray(
        _top_k_top_p_filter_xla(probs, k.astype(jnp.int32), tp, False)
    )
    ref = ref / ref.sum(-1, keepdims=True)
    # same support (up to exact ties) and same renormalized mass
    np.testing.assert_allclose(got.sum(-1), 1.0, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(got, ref, rtol=2e-2, atol=1e-6)


def test_topk_threshold_backend_128k_vocab():
    """Bit-space-bisection exact top-k vs jax.lax.top_k on-chip."""
    from flashinfer_tpu import topk

    bs, vocab, k = 16, 128 * 1024, 2048
    scores = jax.random.normal(jax.random.PRNGKey(0), (bs, vocab)) * 4.0
    _, ix = topk.top_k_values_indices(scores, k, backend="xla")
    _, it = topk.top_k_values_indices(scores, k, backend="threshold")
    for rx, rt in zip(np.asarray(ix), np.asarray(it)):
        assert set(map(int, rx)) == set(i for i in map(int, rt) if i >= 0)


def test_cascade_merge_on_chip():
    """Cascade state algebra: merged split-KV attention == full attention
    (merge_state over flash-kernel LSE outputs)."""
    from flashinfer_tpu.ops.merge import merge_state

    T, N = 512, 2048
    q = jax.random.normal(jax.random.PRNGKey(0), (T, HQ, D), jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(1), (N, HKV, D), jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(2), (N, HKV, D), jnp.bfloat16)
    half = N // 2
    oa, sa = fi.single_prefill_with_kv_cache(
        q, k[:half], v[:half], causal=False, return_lse=True
    )
    ob, sb = fi.single_prefill_with_kv_cache(
        q, k[half:], v[half:], causal=False, return_lse=True
    )
    merged, _ = merge_state(oa, sa, ob, sb)
    ref = fi.single_prefill_with_kv_cache(q, k, v, causal=False)
    np.testing.assert_allclose(
        np.asarray(merged, np.float32), np.asarray(ref, np.float32),
        **BF16_TOL
    )


def test_attention_sink_on_chip():
    """StreamingLLM sink epilogue over the flash kernel's LSE output: the
    sink renormalization must equal a softmax that includes the sink
    logit as an extra zero-value token."""
    from flashinfer_tpu.attention import apply_attention_sink

    T = 1024
    q = jax.random.normal(jax.random.PRNGKey(0), (T, HQ, D), jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(1), (T, HKV, D), jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(2), (T, HKV, D), jnp.bfloat16)
    sink = jax.random.normal(jax.random.PRNGKey(3), (HQ,))
    out, lse = fi.single_prefill_with_kv_cache(
        q, k, v, causal=True, return_lse=True
    )
    got = np.asarray(apply_attention_sink(out, lse, sink), np.float32)
    scale = np.exp(np.asarray(lse, np.float32))
    scale = scale / (scale + np.exp(np.asarray(sink, np.float32))[None, :])
    ref = np.asarray(out, np.float32) * scale[..., None]
    np.testing.assert_allclose(got, ref, rtol=1e-2, atol=1e-2)  # bf16 store


def test_msa_token_granular_on_chip():
    """Token-granular MSA selection + VBSR kernel vs the dense-masked
    oracle under the same per-token selection."""
    from flashinfer_tpu.msa_ops import msa_sparse_attention

    N = 2048
    q = jax.random.normal(jax.random.PRNGKey(0), (N, HQ, D), jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(1), (N, HKV, D), jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(2), (N, HKV, D), jnp.bfloat16)
    out_kernel = msa_sparse_attention(
        q, k, v, top_k=8, backend="pallas", granularity="token"
    )
    out_oracle = msa_sparse_attention(
        q, k, v, top_k=8, backend="xla", granularity="token"
    )
    np.testing.assert_allclose(
        np.asarray(out_kernel, np.float32),
        np.asarray(out_oracle, np.float32), **BF16_TOL
    )


def test_int8_kv_decode_llama_shape():
    """Fused in-register-dequant int8-KV decode at serving shapes."""
    from flashinfer_tpu.ops import paged_decode_attention

    B, PS, ctx = 16, 16, 4096
    ppr = ctx // PS
    npages = B * ppr
    pt = jnp.arange(npages, dtype=jnp.int32).reshape(B, ppr)
    lens = jnp.asarray(
        np.random.default_rng(1).integers(1, ctx + 1, B).astype(np.int32)
    )
    kc = jax.random.normal(
        jax.random.PRNGKey(0), (npages, HKV, PS, D), jnp.bfloat16
    )
    vc = jax.random.normal(
        jax.random.PRNGKey(1), (npages, HKV, PS, D), jnp.bfloat16
    )
    q = jax.random.normal(jax.random.PRNGKey(2), (B, HQ, D), jnp.bfloat16)
    sm = D ** -0.5
    ref = np.asarray(paged_decode_attention(
        q, kc, vc, pt, lens, sm_scale=sm, kv_layout="HND"), np.float32)
    from flashinfer_tpu.quantization import quantize_symmetric_int8

    ks = float(np.abs(np.asarray(kc, np.float32)).max() / 127)
    vs = float(np.abs(np.asarray(vc, np.float32)).max() / 127)
    kq = quantize_symmetric_int8(kc, ks)
    vq = quantize_symmetric_int8(vc, vs)
    o = np.asarray(paged_decode_attention(
        q, kq, vq, pt, lens, sm_scale=sm * ks, kv_layout="HND"),
        np.float32) * vs
    np.testing.assert_allclose(o, ref, rtol=4e-2, atol=4e-2)


def test_fp4_decode_llama_shape():
    """Fused token-pair int4 decode at its best legal ppc (wedge-culprit
    restructure a8f73ff: rolled page loops, selector-dot scales)."""
    from flashinfer_tpu.ops.paged_decode_fp4 import (
        fp4_paged_decode_attention, quantize_kv_int4_paged,
    )
    from flashinfer_tpu.ops import paged_decode_attention

    B, PS, ctx = 16, 16, 2048
    ppr = ctx // PS
    npages = B * ppr
    pt = jnp.arange(npages, dtype=jnp.int32).reshape(B, ppr)
    lens = jnp.full((B,), ctx, jnp.int32)
    kc = jax.random.normal(
        jax.random.PRNGKey(0), (npages, HKV, PS, D), jnp.float32
    )
    vc = jax.random.normal(
        jax.random.PRNGKey(1), (npages, HKV, PS, D), jnp.float32
    )
    q = jax.random.normal(jax.random.PRNGKey(2), (B, HQ, D), jnp.bfloat16)
    k4, ksc = quantize_kv_int4_paged(kc)
    v4, vsc = quantize_kv_int4_paged(vc)
    sm = D ** -0.5
    o = fp4_paged_decode_attention(
        q, k4, ksc, v4, vsc, pt, lens, sm_scale=sm
    )
    ref = paged_decode_attention(
        q, kc.astype(jnp.bfloat16), vc.astype(jnp.bfloat16), pt, lens,
        sm_scale=sm, kv_layout="HND",
    )
    # int4 quantization noise dominates the comparison
    np.testing.assert_allclose(
        np.asarray(o, np.float32), np.asarray(ref, np.float32),
        rtol=1.5e-1, atol=1.5e-1,
    )


def test_moe_gmm_kernel_mixtral_shape():
    """Pallas gather-GMM MoE vs the ragged_dot oracle at Mixtral-8x7B
    hidden/inter dims (token count scaled down)."""
    from flashinfer_tpu import fused_moe as moe

    T, E, K, h, inter = 256, 8, 2, 4096, 14336
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (T, h), jnp.bfloat16)
    w1 = jax.random.normal(
        jax.random.fold_in(key, 1), (E, h, 2 * inter), jnp.bfloat16
    ) * 0.02
    w2 = jax.random.normal(
        jax.random.fold_in(key, 2), (E, inter, h), jnp.bfloat16
    ) * 0.02
    logits = jax.random.normal(jax.random.fold_in(key, 3), (T, E))
    wts, ids = moe.route_renormalize(logits, K)
    ref = moe.fused_moe(x, w1, w2, wts, ids, E, backend="ragged")
    # sorted must pass on hardware (aligned BlockSpec DMAs only); the
    # in-kernel gather variants are attempted so the log records the
    # Mosaic verdict each run — this Mosaic rejects sub-8-row HBM slices
    # ("Slice shape along dimension 0 must be aligned to tiling (8)",
    # banked 2026-07-31)
    rejected = []
    for gv in ("sorted", "stream", "rowcache"):
        try:
            out = moe.fused_moe(x, w1, w2, wts, ids, E, backend="gmm",
                                gather_variant=gv)
        except Exception as e:  # noqa: BLE001 - compiler verdict triage
            if gv != "sorted" and "aligned to tiling" in str(e):
                rejected.append(gv)
                print(f"moe gather variant {gv}: Mosaic still rejects "
                      f"single-row HBM slices ({str(e).splitlines()[0][:100]})")
                continue
            raise
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32),
            rtol=6e-2, atol=6e-2, err_msg=gv,
        )
    if rejected == ["stream", "rowcache"]:
        pytest.xfail("in-kernel gather variants rejected by Mosaic "
                     "(sub-8-row DMA alignment); sorted variant passed")


def test_gather_gmm_rowcache_straddle_on_chip():
    """The rowcache variant's aliased-output boundary merge is a
    HARDWARE-ONLY code path (interpret mode reads out blocks back
    directly, so CPU CI cannot exercise the prev_ref merge or the
    input_output_aliases machinery).  Mid-tile group starts force
    non-consecutive output-block revisits; every row must survive."""
    from flashinfer_tpu.ops.moe_gmm import gather_gmm

    rng = np.random.default_rng(9)
    t_rows, k, n = 128, 512, 512
    m = 256
    sizes = np.asarray([37, 90, 56, 73], np.int32)  # all starts mid-tile
    x = jnp.asarray(rng.standard_normal((t_rows, k)), jnp.bfloat16)
    row_ids = jnp.asarray(rng.integers(0, t_rows, m), jnp.int32)
    rhs = jnp.asarray(rng.standard_normal((4, k, n)) / np.sqrt(k),
                      jnp.bfloat16)
    try:
        out = gather_gmm(x, row_ids, rhs, jnp.asarray(sizes),
                         tm=64, tn=128, tk=128, variant="rowcache")
    except Exception as e:  # noqa: BLE001 - compiler verdict triage
        if "aligned to tiling" in str(e):
            pytest.xfail(
                "Mosaic rejects single-row HBM slices (banked 2026-07-31: "
                "'Slice shape along dimension 0 must be aligned to tiling "
                "(8)'); rowcache gather stays interpret-only until the "
                "compiler relaxes sub-8-row DMA alignment"
            )
        raise
    xs = np.asarray(x, np.float32)[np.asarray(row_ids)]
    offsets = np.concatenate([[0], np.cumsum(sizes)])
    ref = np.zeros((m, n), np.float32)
    for g in range(4):
        ref[offsets[g]:offsets[g + 1]] = (
            xs[offsets[g]:offsets[g + 1]]
            @ np.asarray(rhs, np.float32)[g]
        )
    np.testing.assert_allclose(
        np.asarray(out, np.float32), ref, rtol=5e-2, atol=5e-2
    )


def test_masked_fused_prefill_on_chip():
    """In-kernel packed custom masks (selector-dot bit expansion) on the
    fused prefill kernel vs the dense-oracle, multi-item scoring mask."""
    prefix, items = 512, [128, 96]
    kvl = prefix + sum(items)
    mask = np.asarray(fi.build_multi_item_mask(prefix, items))
    PS = 16
    pages = (kvl + PS - 1) // PS
    packed = np.packbits(mask.reshape(-1).astype(np.uint8),
                         bitorder="little")
    kc = jax.random.normal(
        jax.random.PRNGKey(1), (pages, HKV, PS, D), jnp.bfloat16
    )
    vc = jax.random.normal(
        jax.random.PRNGKey(2), (pages, HKV, PS, D), jnp.bfloat16
    )
    q = jax.random.normal(jax.random.PRNGKey(0), (kvl, HQ, D), jnp.bfloat16)
    w = fi.BatchPrefillWithPagedKVCacheWrapper(
        kv_layout="HND", backend="pallas_fused"
    )
    w.plan(
        np.array([0, kvl]), np.array([0, pages]), np.arange(pages),
        [kvl - (pages - 1) * PS], HQ, HKV, D, PS,
        packed_custom_mask=packed,
    )
    assert "mask_bytes" in w._fused_plan[0]
    out = w.run(q, (kc, vc))
    kflat = jnp.swapaxes(kc, 1, 2).reshape(-1, HKV, D)[:kvl]
    vflat = jnp.swapaxes(vc, 1, 2).reshape(-1, HKV, D)[:kvl]
    ref = fi.single_prefill_with_kv_cache(
        q, kflat, vflat, custom_mask=jnp.asarray(mask)
    )
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), **BF16_TOL
    )


def test_alibi_flash_kernel_on_chip():
    """In-kernel ALiBi bias (f32 slopes as a scalar-prefetch operand +
    per-head SMEM read) must Mosaic-compile and match the dense oracle."""
    q_len, kv_len = 256, 1024
    key = jax.random.PRNGKey(5)
    q = jax.random.normal(key, (q_len, HQ, D), jnp.bfloat16)
    k = jax.random.normal(jax.random.fold_in(key, 1), (kv_len, HKV, D),
                          jnp.bfloat16)
    v = jax.random.normal(jax.random.fold_in(key, 2), (kv_len, HKV, D),
                          jnp.bfloat16)
    o = fi.single_prefill_with_kv_cache(
        q, k, v, causal=True, pos_encoding_mode="ALIBI", backend="pallas"
    )
    ref = fi.single_prefill_with_kv_cache(
        q, k, v, causal=True, pos_encoding_mode="ALIBI"
    )
    np.testing.assert_allclose(
        np.asarray(o, np.float32), np.asarray(ref, np.float32), **BF16_TOL
    )


def test_trace_events_prefill_on_chip():
    """The in-kernel device-tag tracing variant (trace_events=True) must
    Mosaic-compile and emit decodable tags on hardware — the last prefill
    variant of the round-3/4 backlog without an on-chip verdict."""
    from flashinfer_tpu import profiler
    from flashinfer_tpu.ops.paged_prefill import (
        build_prefill_work_units, fused_paged_prefill,
    )

    PS = 16
    qo_len, kv_len = 256, 512
    pages = kv_len // PS
    plan_np = build_prefill_work_units(
        np.array([0, qo_len]), np.array([0, pages]),
        np.arange(pages, dtype=np.int32), np.array([kv_len], np.int64),
        block_q=128, pages_per_chunk=8, page_size=PS,
    )
    num_units = plan_np.pop("num_units")
    plan_np.pop("block_q"), plan_np.pop("pages_per_chunk")
    plan_np.pop("stats")
    plan = {k: jnp.asarray(v) for k, v in plan_np.items()}
    q = jax.random.normal(jax.random.PRNGKey(0), (qo_len, HQ, D),
                          jnp.bfloat16)
    kc = jax.random.normal(jax.random.PRNGKey(1), (pages, HKV, PS, D),
                           jnp.bfloat16)
    vc = jax.random.normal(jax.random.PRNGKey(2), (pages, HKV, PS, D),
                           jnp.bfloat16)
    out, events = fused_paged_prefill(
        q, kc, vc, plan, num_units=num_units, block_q=128,
        pages_per_chunk=8, trace_events=True,
    )
    # numerics unchanged by tracing
    out_plain = fused_paged_prefill(
        q, kc, vc, plan, num_units=num_units, block_q=128,
        pages_per_chunk=8,
    )
    np.testing.assert_array_equal(
        np.asarray(out, np.float32), np.asarray(out_plain, np.float32)
    )
    ev = np.asarray(events)
    assert ev.shape == (HKV, num_units)
    for h in range(HKV):
        for u in range(num_units):
            blk, grp, ei, et, sm = profiler.decode_tag(
                int(ev[h, u]), num_units, 1
            )
            assert (sm, blk, et) == (h, u, 2), (h, u, ev[h, u])


def test_gdn_pallas_kernel_on_chip():
    """Fused chunked GDN kernel vs the exact recurrence at model shapes
    (normalized keys — the delta-rule operating regime)."""
    from flashinfer_tpu.gdn import gdn_prefill
    from flashinfer_tpu.ops.gdn_kernel import gdn_chunk_prefill_pallas

    rng = np.random.default_rng(0)
    B, L, H, dk, dv = 2, 1024, 4, 128, 128
    qn = rng.standard_normal((B, L, H, dk))
    kn = rng.standard_normal((B, L, H, dk))
    q = jnp.asarray(qn / np.linalg.norm(qn, axis=-1, keepdims=True),
                    jnp.bfloat16)
    k = jnp.asarray(kn / np.linalg.norm(kn, axis=-1, keepdims=True),
                    jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((B, L, H, dv)), jnp.bfloat16)
    alpha = jnp.asarray(np.exp(-0.1 * rng.random((B, L, H))), jnp.float32)
    beta = jnp.asarray(rng.random((B, L, H)), jnp.float32)
    o_ref, s_ref = gdn_prefill(q, k, v, alpha, beta)
    o, s = gdn_chunk_prefill_pallas(q, k, v, alpha, beta)
    np.testing.assert_allclose(
        np.asarray(o, np.float32), np.asarray(o_ref, np.float32),
        rtol=4e-2, atol=4e-2,
    )
    np.testing.assert_allclose(
        np.asarray(s), np.asarray(s_ref), rtol=4e-2, atol=4e-2
    )


def test_mamba_ssd_pallas_kernel_on_chip():
    """Fused SSD kernel vs the XLA chunked form at Mamba-2-ish shapes."""
    from flashinfer_tpu.mamba import mamba_chunk_scan_combined

    rng = np.random.default_rng(1)
    B, L, H, G, dim, ds = 2, 1024, 8, 2, 64, 128
    x = jnp.asarray(rng.standard_normal((B, L, H, dim)), jnp.bfloat16)
    dt = jnp.asarray(rng.random((B, L, H)) + 0.1, jnp.float32)
    A = jnp.asarray(-np.abs(rng.standard_normal(H)) - 0.1, jnp.float32)
    Bm = jnp.asarray(rng.standard_normal((B, L, G, ds)) * 0.3, jnp.bfloat16)
    Cm = jnp.asarray(rng.standard_normal((B, L, G, ds)) * 0.3, jnp.bfloat16)
    y_ref, s_ref = mamba_chunk_scan_combined(x, dt, A, Bm, Cm, chunk_size=64)
    y, s = mamba_chunk_scan_combined(x, dt, A, Bm, Cm, backend="pallas")
    np.testing.assert_allclose(
        np.asarray(y, np.float32), np.asarray(y_ref, np.float32),
        rtol=5e-2, atol=5e-2,
    )
    np.testing.assert_allclose(
        np.asarray(s), np.asarray(s_ref), rtol=5e-2, atol=5e-2
    )


def test_kda_pallas_kernel_on_chip():
    """Fused KDA kernel vs the exact recurrence (normalized keys,
    trained-gate-range decay)."""
    from flashinfer_tpu.gdn import kda_chunk_prefill, kda_prefill

    rng = np.random.default_rng(2)
    B, L, H, dk, dv = 1, 512, 4, 128, 128
    qn = rng.standard_normal((B, L, H, dk))
    kn = rng.standard_normal((B, L, H, dk))
    q = jnp.asarray(qn / np.linalg.norm(qn, axis=-1, keepdims=True),
                    jnp.bfloat16)
    k = jnp.asarray(kn / np.linalg.norm(kn, axis=-1, keepdims=True),
                    jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((B, L, H, dv)), jnp.bfloat16)
    alpha = jnp.asarray(np.exp(-0.05 * rng.random((B, L, H, dk))),
                        jnp.float32)
    beta = jnp.asarray(rng.random((B, L, H)), jnp.float32)
    o_ref, s_ref = kda_prefill(q, k, v, alpha, beta)
    o, s = kda_chunk_prefill(q, k, v, alpha, beta, backend="pallas")
    np.testing.assert_allclose(
        np.asarray(o, np.float32), np.asarray(o_ref, np.float32),
        rtol=5e-2, atol=5e-2,
    )
    np.testing.assert_allclose(
        np.asarray(s), np.asarray(s_ref), rtol=5e-2, atol=5e-2
    )
