"""Migration proof #15: mechanical port of the reference test file
``/root/reference/tests/attention/test_attention_sink.py`` (the main
``test_attention_sink`` matrix) run against ``flashinfer_tpu``.

Same porting contract as tests/test_ported_batch_prefill.py: reference
matrix verbatim, reference call sequences — BOTH halves:

1. ``BatchPrefillWithRaggedKVCacheWrapper(ws, kv_layout, backend=,
   jit_args=, jit_kwargs=)`` with the attention-sink custom-variant
   declaration, then ``run(q, k, v, sink, sm_scale)`` POSITIONAL (the
   declared additional tensor/scalar order);
2. ``BatchAttentionWithAttentionSinkWrapper`` (paged, page_size=1) with
   the standard paged-prefill plan and ``run(q, (k, v), sink,
   sm_scale)``, including the reference's fragmented-page-pool
   scenario.

Oracle = the reference's ``sink_attention_unified`` prefill mode
(tests/test_helpers/sink_attention_reference.py: sink logit joins the
softmax denominator per head) transcribed to numpy f64.  The jit_args
URI/dtype fields are inert on TPU (no jinja codegen) but the DECLARED
additional names define the positional run() extras — that contract is
what this file proves.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import flashinfer_tpu as fi
from tests.test_ported_batch_prefill import _sample, _work_gate

_HEAD_DIM = 128


def _sink_attention_ref(batch_size, q, k, v, sink, window_left, causal,
                        sm_scale):
    """Reference sink_attention_unified, mode="prefill"
    (sink_attention_reference.py:39-377) in f64: per-head sink logit
    joins the softmax denominator; causal mask is bottom-right aligned;
    window applies with or without causal."""
    q = np.asarray(q, np.float64)
    k = np.asarray(k, np.float64)
    v = np.asarray(v, np.float64)
    sink = np.asarray(sink, np.float64)
    qo_len = q.shape[0] // batch_size
    kv_len = k.shape[0] // batch_size
    hq, d = q.shape[1], q.shape[2]
    hkv = k.shape[1]
    if hq != hkv:
        k = np.repeat(k, hq // hkv, axis=1)
        v = np.repeat(v, hq // hkv, axis=1)
    logits = np.einsum(
        "bmhd,bnhd->bhmn",
        q.reshape(batch_size, qo_len, hq, d),
        k.reshape(batch_size, kv_len, hq, d)) * sm_scale
    row = np.arange(qo_len)[:, None]
    col = np.arange(kv_len)[None, :]
    if causal:
        mask = (kv_len - qo_len + row) >= col
        if window_left >= 0:
            mask &= (row - window_left) <= col
    else:
        mask = np.ones((qo_len, kv_len), bool)
        if window_left >= 0:
            mask = (row - window_left) <= col
    logits = np.where(mask[None, None], logits, -np.inf)
    # sink softmax: per-head sink logit appended to the denominator
    m = np.maximum(logits.max(-1), sink[None, :, None])  # [b, h, m]
    num = np.exp(logits - m[..., None])
    denom = num.sum(-1) + np.exp(sink[None, :, None] - m)
    p = num / denom[..., None]
    o = np.einsum(
        "bhmn,bnhd->bmhd", p, v.reshape(batch_size, kv_len, hq, -1))
    return o.reshape(batch_size * qo_len, hq, -1)


_SINK_JIT_ARGS = (
    "batch_prefill_attention_sink_tpu",  # uri (inert)
    None, None, None, None,              # dtypes/idtype (inert)
    _HEAD_DIM, _HEAD_DIM,                # hidden dims (inert)
    ["sink"], ["float"],                 # additional tensors
    ["sm_scale"], ["double"],            # additional scalars
    "AttentionSink", "",                 # variant name / decl (inert)
)


@pytest.mark.parametrize(
    "dtype,batch_size,seq_len,num_qo_heads,num_kv_heads,window_left,"
    "causal,backend",
    _sample(
        "attention_sink",
        [jnp.float16, jnp.bfloat16], [1, 4, 16], [1, 4, 16, 128], [32],
        [8, 32], [-1, 128], [True, False], ["fa2", "fa3"],
        specials=((5, 128), (6, False)),  # keep windowed + non-causal cells
    ),
)
def test_attention_sink(dtype, batch_size, seq_len, num_qo_heads,
                        num_kv_heads, window_left, causal, backend):
    """Reference test_attention_sink (test_attention_sink.py:158)."""
    _work_gate(batch_size, seq_len, seq_len, num_qo_heads, _HEAD_DIM)
    sm_scale = 1.0 / math.sqrt(_HEAD_DIM)
    key = jax.random.PRNGKey(42)
    q = jax.random.normal(
        key, (batch_size * seq_len, num_qo_heads, _HEAD_DIM), dtype)
    k = jax.random.normal(
        jax.random.fold_in(key, 1),
        (batch_size * seq_len, num_kv_heads, _HEAD_DIM), dtype)
    v = jax.random.normal(
        jax.random.fold_in(key, 2),
        (batch_size * seq_len, num_kv_heads, _HEAD_DIM), dtype)
    sink = jax.random.uniform(
        jax.random.fold_in(key, 3), (num_qo_heads,), jnp.float32) * 5

    o_ref = _sink_attention_ref(
        batch_size, q, k, v, sink, window_left, causal, sm_scale)
    tol = dict(rtol=1e-3, atol=1e-3) if dtype == jnp.float16 \
        else dict(rtol=1e-2, atol=1e-2)

    indptr = np.arange(
        0, batch_size * seq_len + 1, seq_len, dtype=np.int32)
    # ragged custom-variant + paged + fragmented pool (reference seed
    # contract: 42 + total_pages)
    _run_both_wrappers(
        q, k, v, sink, sm_scale, indptr, indptr, causal, window_left,
        backend, dtype, o_ref, tol,
        frag_seed=42 + batch_size * seq_len)


def _sink_varlen_ref(q, k, v, sink, window_left, causal, sm_scale,
                     qo_indptr, kv_indptr):
    """Reference sink_attention_varlen_ref
    (sink_attention_reference.py:124, per-request loop): absolute query
    positions (kv_len_i - qo_len_i + row), window applied with or
    without causal — the general oracle; prefill/incremental/chunk are
    the uniform-length special cases."""
    q = np.asarray(q, np.float64)
    k = np.asarray(k, np.float64)
    v = np.asarray(v, np.float64)
    sink = np.asarray(sink, np.float64)
    hq, hkv = q.shape[1], k.shape[1]
    if hq != hkv:
        k = np.repeat(k, hq // hkv, axis=1)
        v = np.repeat(v, hq // hkv, axis=1)
    outs = []
    for i in range(len(qo_indptr) - 1):
        qi = q[qo_indptr[i]:qo_indptr[i + 1]]
        ki = k[kv_indptr[i]:kv_indptr[i + 1]]
        vi = v[kv_indptr[i]:kv_indptr[i + 1]]
        qo_len, kv_len = qi.shape[0], ki.shape[0]
        logits = np.einsum("qhd,khd->hqk", qi, ki) * sm_scale
        row = np.arange(qo_len)[:, None]
        col = np.arange(kv_len)[None, :]
        pos = kv_len - qo_len + row
        mask = (pos >= col) if causal else np.ones((qo_len, kv_len), bool)
        if window_left >= 0:
            mask = mask & ((pos - window_left) <= col)
        logits = np.where(mask[None], logits, -np.inf)
        m = np.maximum(logits.max(-1), sink[:, None])
        num = np.exp(logits - m[..., None])
        denom = num.sum(-1) + np.exp(sink[:, None] - m)
        p = num / denom[..., None]
        outs.append(np.einsum("hqk,khd->qhd", p, vi))
    return np.concatenate(outs, 0)


def _run_both_wrappers(q, k, v, sink, sm_scale, qo_indptr, kv_indptr,
                       causal, window_left, backend, dtype, o_ref, tol,
                       frag_seed=None):
    """The reference's repeated wrapper checks: ragged custom-variant +
    paged sink wrapper at page_size=1, and (when ``frag_seed`` is given,
    per the reference's per-scenario seeds) the fragmented-page-pool
    paged variant."""
    wrapper = fi.BatchPrefillWithRaggedKVCacheWrapper(
        jnp.empty(1024, jnp.uint8), kv_layout="NHD", backend=backend,
        jit_args=_SINK_JIT_ARGS,
        jit_kwargs={"use_sliding_window": window_left >= 0})
    wrapper.plan(qo_indptr, kv_indptr, q.shape[1], k.shape[1], _HEAD_DIM,
                 causal=causal, window_left=window_left,
                 q_data_type=dtype, kv_data_type=dtype)
    o = wrapper.run(q, k, v, sink, sm_scale)
    np.testing.assert_allclose(
        np.asarray(o, np.float32), o_ref.astype(np.float32), **tol)

    wrapper_paged = fi.BatchAttentionWithAttentionSinkWrapper(
        jnp.empty(1024, jnp.uint8), kv_layout="NHD", backend=backend,
        q_data_type=dtype, kv_data_type=dtype,
        head_dim_qk=_HEAD_DIM, head_dim_vo=_HEAD_DIM,
        window_left=window_left)
    kv_indices = np.arange(int(kv_indptr[-1]), dtype=np.int32)
    last_page_len = np.full((len(kv_indptr) - 1,), 1, np.int32)
    wrapper_paged.plan(
        qo_indptr, kv_indptr, kv_indices, last_page_len, q.shape[1],
        k.shape[1], _HEAD_DIM, 1, causal=causal, window_left=window_left,
        q_data_type=dtype, kv_data_type=dtype, non_blocking=True)
    o_paged = wrapper_paged.run(q, (k[:, None], v[:, None]), sink, sm_scale)
    np.testing.assert_allclose(
        np.asarray(o_paged, np.float32), o_ref.astype(np.float32), **tol)

    total_pages = int(kv_indptr[-1])
    if frag_seed is not None and total_pages > 1:
        # fragmented page pool ("production scenario"): same data behind
        # non-contiguous page indices must give identical results
        import random

        rnd = random.Random(frag_seed)
        all_pages = list(range(0, total_pages * 2))
        occupied = set(rnd.sample(
            all_pages, min(total_pages, len(all_pages) // 2)))
        available = [p for p in all_pages if p not in occupied]
        kv_indices_frag = np.asarray(available[:total_pages], np.int32)
        k_np = np.asarray(k, np.float32)
        v_np = np.asarray(v, np.float32)
        k_frag = np.zeros(
            (total_pages * 2, 1) + k_np.shape[1:], np.float32)
        v_frag = np.zeros_like(k_frag)
        k_frag[kv_indices_frag, 0] = k_np
        v_frag[kv_indices_frag, 0] = v_np
        wrapper_frag = fi.BatchAttentionWithAttentionSinkWrapper(
            jnp.empty(1024, jnp.uint8), kv_layout="NHD", backend=backend,
            q_data_type=dtype, kv_data_type=dtype,
            head_dim_qk=_HEAD_DIM, head_dim_vo=_HEAD_DIM,
            window_left=window_left)
        wrapper_frag.plan(
            qo_indptr, kv_indptr, kv_indices_frag, last_page_len,
            q.shape[1], k.shape[1], _HEAD_DIM, 1, causal=causal,
            window_left=window_left, q_data_type=dtype,
            kv_data_type=dtype, non_blocking=True)
        o_frag = wrapper_frag.run(
            q, (jnp.asarray(k_frag, dtype), jnp.asarray(v_frag, dtype)),
            sink, sm_scale)
        np.testing.assert_allclose(
            np.asarray(o_frag, np.float32), o_ref.astype(np.float32),
            **tol)


@pytest.mark.parametrize(
    "dtype,batch_size,initial_seq_len,num_generation_steps,num_qo_heads,"
    "num_kv_heads,window_left,causal,backend",
    _sample(
        "sink_incremental",
        [jnp.float16, jnp.bfloat16], [1, 4, 16], [32, 128], [1, 2, 4],
        [32], [8, 32], [-1, 128], [True, False], ["fa2", "fa3"],
    ),
)
def test_attention_sink_incremental_generation(
        dtype, batch_size, initial_seq_len, num_generation_steps,
        num_qo_heads, num_kv_heads, window_left, causal, backend):
    """Reference test_attention_sink_incremental_generation
    (test_attention_sink.py:361): q_len=1 per step, cache grows; both
    wrappers checked at every step."""
    _work_gate(batch_size, 1,
               initial_seq_len + num_generation_steps, num_qo_heads,
               _HEAD_DIM)
    sm_scale = 1.0 / math.sqrt(_HEAD_DIM)
    key = jax.random.PRNGKey(42)
    k_cache = jax.random.normal(
        key, (batch_size, initial_seq_len, num_kv_heads, _HEAD_DIM), dtype)
    v_cache = jax.random.normal(
        jax.random.fold_in(key, 1),
        (batch_size, initial_seq_len, num_kv_heads, _HEAD_DIM), dtype)
    sink = jax.random.uniform(
        jax.random.fold_in(key, 2), (num_qo_heads,), jnp.float32) * 5
    tol = dict(rtol=1e-3, atol=1e-3) if dtype == jnp.float16 \
        else dict(rtol=1e-2, atol=1e-2)

    k_acc = v_acc = None
    for step in range(num_generation_steps):
        cur_len = initial_seq_len + step
        skey = jax.random.fold_in(key, 100 + step)
        q_new = jax.random.normal(
            skey, (batch_size, num_qo_heads, _HEAD_DIM), dtype)
        k_new = jax.random.normal(
            jax.random.fold_in(skey, 1),
            (batch_size, 1, num_kv_heads, _HEAD_DIM), dtype)
        v_new = jax.random.normal(
            jax.random.fold_in(skey, 2),
            (batch_size, 1, num_kv_heads, _HEAD_DIM), dtype)
        if step == 0:
            k_cur, v_cur = k_cache, v_cache
        else:
            k_cur = jnp.concatenate([k_cache, k_acc], axis=1)
            v_cur = jnp.concatenate([v_cache, v_acc], axis=1)

        q_flat = q_new.reshape(batch_size, num_qo_heads, _HEAD_DIM)
        k_flat = k_cur.reshape(batch_size * cur_len, num_kv_heads,
                               _HEAD_DIM)
        v_flat = v_cur.reshape(batch_size * cur_len, num_kv_heads,
                               _HEAD_DIM)
        qo_indptr = np.arange(0, batch_size + 1, dtype=np.int32)
        kv_indptr = np.arange(
            0, batch_size * cur_len + 1, cur_len, dtype=np.int32)
        o_ref = _sink_varlen_ref(
            q_flat, k_flat, v_flat, sink, window_left, causal, sm_scale,
            qo_indptr, kv_indptr)
        _run_both_wrappers(
            q_flat, k_flat, v_flat, sink, sm_scale, qo_indptr, kv_indptr,
            causal, window_left, backend, dtype, o_ref, tol,
            frag_seed=42 + step + cur_len)

        k_acc = k_new if step == 0 else jnp.concatenate(
            [k_acc, k_new], axis=1)
        v_acc = v_new if step == 0 else jnp.concatenate(
            [v_acc, v_new], axis=1)


@pytest.mark.parametrize(
    "dtype,batch_size,chunk_size,historical_len,num_qo_heads,"
    "num_kv_heads,window_left,causal,backend",
    _sample(
        "sink_chunk",
        [jnp.float16, jnp.bfloat16], [1, 4, 16], [128, 256], [256, 512],
        [32], [8, 32], [-1, 128], [True, False], ["fa2", "fa3"],
        # pin the windowed and non-causal cells: the non-causal+window
        # combination is the one the REFERENCE xfails (its kernel
        # disagrees with its own oracle) and this port runs
        specials=((6, 128), (7, False)),
    ),
)
def test_attention_sink_chunk_prefill(
        dtype, batch_size, chunk_size, historical_len, num_qo_heads,
        num_kv_heads, window_left, causal, backend):
    """Reference test_attention_sink_chunk_prefill
    (test_attention_sink.py:627).  The reference XFAILS its non-causal +
    sliding-window cells (their kernel disagrees with their own oracle
    after PR#1661); the TPU implementation uses absolute query positions
    exactly like the oracle, so those cells RUN here."""
    if chunk_size >= historical_len:
        pytest.skip(
            "chunk_size should be smaller than historical_len for "
            "meaningful chunk prefill test")
    total_kv_len = historical_len + chunk_size
    _work_gate(batch_size, chunk_size, total_kv_len, num_qo_heads,
               _HEAD_DIM)
    sm_scale = 1.0 / math.sqrt(_HEAD_DIM)
    key = jax.random.PRNGKey(7)
    q_chunk = jax.random.normal(
        key, (batch_size * chunk_size, num_qo_heads, _HEAD_DIM), dtype)
    k_all = jax.random.normal(
        jax.random.fold_in(key, 1),
        (batch_size * total_kv_len, num_kv_heads, _HEAD_DIM), dtype)
    v_all = jax.random.normal(
        jax.random.fold_in(key, 2),
        (batch_size * total_kv_len, num_kv_heads, _HEAD_DIM), dtype)
    sink = jax.random.uniform(
        jax.random.fold_in(key, 3), (num_qo_heads,), jnp.float32) * 5
    qo_indptr = np.arange(
        0, batch_size * chunk_size + 1, chunk_size, dtype=np.int32)
    kv_indptr = np.arange(
        0, batch_size * total_kv_len + 1, total_kv_len, dtype=np.int32)
    o_ref = _sink_varlen_ref(
        q_chunk, k_all, v_all, sink, window_left, causal, sm_scale,
        qo_indptr, kv_indptr)
    tol = dict(rtol=1e-3, atol=1e-3) if dtype == jnp.float16 \
        else dict(rtol=1e-2, atol=1e-2)
    _run_both_wrappers(
        q_chunk, k_all, v_all, sink, sm_scale, qo_indptr, kv_indptr,
        causal, window_left, backend, dtype, o_ref, tol)


@pytest.mark.parametrize(
    "dtype,indptr_config,num_qo_heads,num_kv_heads,window_left,causal,"
    "backend",
    _sample(
        "sink_varlen",
        [jnp.float16, jnp.bfloat16],
        [
            ([0, 32, 64, 128, 256], [0, 128, 256, 512, 1024],
             "4 requests: prefill-like scenarios"),
            ([0, 1, 2, 3, 4], [0, 128, 256, 384, 512],
             "4 requests: incremental generation"),
            ([0, 50, 150, 200], [0, 200, 600, 800],
             "3 requests: mixed lengths"),
            ([0, 100, 200, 400, 600, 1000], [0, 300, 600, 1200, 1800, 3000],
             "5 requests: large sequences"),
            ([0, 16, 32, 96, 128], [0, 64, 128, 384, 512],
             "4 requests: chunk prefill-like"),
        ],
        [32], [8, 32], [-1, 128], [True, False], ["fa2", "fa3"],
        # pin a sliding-window and a causal cell (the abs-position
        # window path is this oracle's reason to exist)
        specials=((4, 128), (5, True)),
    ),
)
def test_attention_sink_varlen(dtype, indptr_config, num_qo_heads,
                               num_kv_heads, window_left, causal, backend):
    """Reference test_attention_sink_varlen (test_attention_sink.py:891)."""
    qo_indptr, kv_indptr, description = indptr_config
    if len(qo_indptr) != len(kv_indptr):
        pytest.skip(
            f"qo_indptr and kv_indptr must have same batch size for "
            f"{description}")
    batch_size = len(qo_indptr) - 1
    if causal:
        for i in range(batch_size):
            if qo_indptr[i + 1] - qo_indptr[i] > \
                    kv_indptr[i + 1] - kv_indptr[i]:
                pytest.skip("qo_len > kv_len not supported for causal "
                            "attention in varlen mode")
    total_qo, total_kv = qo_indptr[-1], kv_indptr[-1]
    _work_gate(1, total_qo, total_kv, num_qo_heads, _HEAD_DIM)
    sm_scale = 1.0 / math.sqrt(_HEAD_DIM)
    key = jax.random.PRNGKey(11)
    q = jax.random.normal(key, (total_qo, num_qo_heads, _HEAD_DIM), dtype)
    k = jax.random.normal(
        jax.random.fold_in(key, 1), (total_kv, num_kv_heads, _HEAD_DIM),
        dtype)
    v = jax.random.normal(
        jax.random.fold_in(key, 2), (total_kv, num_kv_heads, _HEAD_DIM),
        dtype)
    sink = jax.random.uniform(
        jax.random.fold_in(key, 3), (num_qo_heads,), jnp.float32) * 5
    qo_np = np.asarray(qo_indptr, np.int32)
    kv_np = np.asarray(kv_indptr, np.int32)
    o_ref = _sink_varlen_ref(
        q, k, v, sink, window_left, causal, sm_scale, qo_np, kv_np)
    tol = dict(rtol=1e-3, atol=1e-3) if dtype == jnp.float16 \
        else dict(rtol=1e-2, atol=1e-2)
    _run_both_wrappers(
        q, k, v, sink, sm_scale, qo_np, kv_np, causal, window_left,
        backend, dtype, o_ref, tol)
