"""Migration proof #15: mechanical port of the reference test file
``/root/reference/tests/attention/test_attention_sink.py`` (the main
``test_attention_sink`` matrix) run against ``flashinfer_tpu``.

Same porting contract as tests/test_ported_batch_prefill.py: reference
matrix verbatim, reference call sequences — BOTH halves:

1. ``BatchPrefillWithRaggedKVCacheWrapper(ws, kv_layout, backend=,
   jit_args=, jit_kwargs=)`` with the attention-sink custom-variant
   declaration, then ``run(q, k, v, sink, sm_scale)`` POSITIONAL (the
   declared additional tensor/scalar order);
2. ``BatchAttentionWithAttentionSinkWrapper`` (paged, page_size=1) with
   the standard paged-prefill plan and ``run(q, (k, v), sink,
   sm_scale)``, including the reference's fragmented-page-pool
   scenario.

Oracle = the reference's ``sink_attention_unified`` prefill mode
(tests/test_helpers/sink_attention_reference.py: sink logit joins the
softmax denominator per head) transcribed to numpy f64.  The jit_args
URI/dtype fields are inert on TPU (no jinja codegen) but the DECLARED
additional names define the positional run() extras — that contract is
what this file proves.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import flashinfer_tpu as fi
from tests.test_ported_batch_prefill import _sample, _work_gate

_HEAD_DIM = 128


def _sink_attention_ref(batch_size, q, k, v, sink, window_left, causal,
                        sm_scale):
    """Reference sink_attention_unified, mode="prefill"
    (sink_attention_reference.py:39-377) in f64: per-head sink logit
    joins the softmax denominator; causal mask is bottom-right aligned;
    window applies with or without causal."""
    q = np.asarray(q, np.float64)
    k = np.asarray(k, np.float64)
    v = np.asarray(v, np.float64)
    sink = np.asarray(sink, np.float64)
    qo_len = q.shape[0] // batch_size
    kv_len = k.shape[0] // batch_size
    hq, d = q.shape[1], q.shape[2]
    hkv = k.shape[1]
    if hq != hkv:
        k = np.repeat(k, hq // hkv, axis=1)
        v = np.repeat(v, hq // hkv, axis=1)
    logits = np.einsum(
        "bmhd,bnhd->bhmn",
        q.reshape(batch_size, qo_len, hq, d),
        k.reshape(batch_size, kv_len, hq, d)) * sm_scale
    row = np.arange(qo_len)[:, None]
    col = np.arange(kv_len)[None, :]
    if causal:
        mask = (kv_len - qo_len + row) >= col
        if window_left >= 0:
            mask &= (row - window_left) <= col
    else:
        mask = np.ones((qo_len, kv_len), bool)
        if window_left >= 0:
            mask = (row - window_left) <= col
    logits = np.where(mask[None, None], logits, -np.inf)
    # sink softmax: per-head sink logit appended to the denominator
    m = np.maximum(logits.max(-1), sink[None, :, None])  # [b, h, m]
    num = np.exp(logits - m[..., None])
    denom = num.sum(-1) + np.exp(sink[None, :, None] - m)
    p = num / denom[..., None]
    o = np.einsum(
        "bhmn,bnhd->bmhd", p, v.reshape(batch_size, kv_len, hq, -1))
    return o.reshape(batch_size * qo_len, hq, -1)


_SINK_JIT_ARGS = (
    "batch_prefill_attention_sink_tpu",  # uri (inert)
    None, None, None, None,              # dtypes/idtype (inert)
    _HEAD_DIM, _HEAD_DIM,                # hidden dims (inert)
    ["sink"], ["float"],                 # additional tensors
    ["sm_scale"], ["double"],            # additional scalars
    "AttentionSink", "",                 # variant name / decl (inert)
)


@pytest.mark.parametrize(
    "dtype,batch_size,seq_len,num_qo_heads,num_kv_heads,window_left,"
    "causal,backend",
    _sample(
        "attention_sink",
        [jnp.float16, jnp.bfloat16], [1, 4, 16], [1, 4, 16, 128], [32],
        [8, 32], [-1, 128], [True, False], ["fa2", "fa3"],
        specials=((5, 128), (6, False)),  # keep windowed + non-causal cells
    ),
)
def test_attention_sink(dtype, batch_size, seq_len, num_qo_heads,
                        num_kv_heads, window_left, causal, backend):
    """Reference test_attention_sink (test_attention_sink.py:158)."""
    _work_gate(batch_size, seq_len, seq_len, num_qo_heads, _HEAD_DIM)
    sm_scale = 1.0 / math.sqrt(_HEAD_DIM)
    key = jax.random.PRNGKey(42)
    q = jax.random.normal(
        key, (batch_size * seq_len, num_qo_heads, _HEAD_DIM), dtype)
    k = jax.random.normal(
        jax.random.fold_in(key, 1),
        (batch_size * seq_len, num_kv_heads, _HEAD_DIM), dtype)
    v = jax.random.normal(
        jax.random.fold_in(key, 2),
        (batch_size * seq_len, num_kv_heads, _HEAD_DIM), dtype)
    sink = jax.random.uniform(
        jax.random.fold_in(key, 3), (num_qo_heads,), jnp.float32) * 5

    o_ref = _sink_attention_ref(
        batch_size, q, k, v, sink, window_left, causal, sm_scale)
    tol = dict(rtol=1e-3, atol=1e-3) if dtype == jnp.float16 \
        else dict(rtol=1e-2, atol=1e-2)

    # ---- ragged wrapper with the custom-variant jit_args declaration ----
    wrapper = fi.BatchPrefillWithRaggedKVCacheWrapper(
        jnp.empty(1024, jnp.uint8), kv_layout="NHD", backend=backend,
        jit_args=_SINK_JIT_ARGS,
        jit_kwargs={"use_sliding_window": window_left >= 0})
    indptr = np.arange(
        0, batch_size * seq_len + 1, seq_len, dtype=np.int32)
    wrapper.plan(indptr, indptr, num_qo_heads, num_kv_heads, _HEAD_DIM,
                 causal=causal, window_left=window_left,
                 q_data_type=dtype, kv_data_type=dtype)
    o = wrapper.run(q, k, v, sink, sm_scale)
    np.testing.assert_allclose(
        np.asarray(o, np.float32), o_ref.astype(np.float32), **tol)

    # ---- paged sink wrapper, page_size=1 (reference second half) ----
    wrapper_paged = fi.BatchAttentionWithAttentionSinkWrapper(
        jnp.empty(1024, jnp.uint8), kv_layout="NHD", backend=backend,
        q_data_type=dtype, kv_data_type=dtype,
        head_dim_qk=_HEAD_DIM, head_dim_vo=_HEAD_DIM,
        window_left=window_left)
    kv_indices = np.arange(0, batch_size * seq_len, dtype=np.int32)
    last_page_len = np.full((batch_size,), 1, np.int32)
    wrapper_paged.plan(
        indptr, indptr, kv_indices, last_page_len, num_qo_heads,
        num_kv_heads, _HEAD_DIM, 1, causal=causal,
        window_left=window_left, q_data_type=dtype, kv_data_type=dtype,
        non_blocking=True)
    o_paged = wrapper_paged.run(
        q, (k[:, None], v[:, None]), sink, sm_scale)
    np.testing.assert_allclose(
        np.asarray(o_paged, np.float32), o_ref.astype(np.float32), **tol)

    # ---- fragmented page pool (reference "production scenario") ----
    total_pages = batch_size * seq_len
    if total_pages > 1:
        import random

        random.seed(42 + total_pages)
        all_pages = list(range(0, total_pages * 2))
        occupied = set(random.sample(
            all_pages, min(total_pages, len(all_pages) // 2)))
        available = [p for p in all_pages if p not in occupied]
        kv_indices_frag = np.asarray(available[:total_pages], np.int32)
        k_frag = np.zeros(
            (total_pages * 2, 1, num_kv_heads, _HEAD_DIM), np.float32)
        v_frag = np.zeros_like(k_frag)
        k_np, v_np = np.asarray(k, np.float32), np.asarray(v, np.float32)
        for i, page_idx in enumerate(kv_indices_frag):
            k_frag[page_idx, 0] = k_np[i]
            v_frag[page_idx, 0] = v_np[i]
        wrapper_frag = fi.BatchAttentionWithAttentionSinkWrapper(
            jnp.empty(1024, jnp.uint8), kv_layout="NHD", backend=backend,
            q_data_type=dtype, kv_data_type=dtype,
            head_dim_qk=_HEAD_DIM, head_dim_vo=_HEAD_DIM,
            window_left=window_left)
        wrapper_frag.plan(
            indptr, indptr, kv_indices_frag, last_page_len, num_qo_heads,
            num_kv_heads, _HEAD_DIM, 1, causal=causal,
            window_left=window_left, q_data_type=dtype, kv_data_type=dtype,
            non_blocking=True)
        o_frag = wrapper_frag.run(
            q, (jnp.asarray(k_frag, dtype), jnp.asarray(v_frag, dtype)),
            sink, sm_scale)
        np.testing.assert_allclose(
            np.asarray(o_frag, np.float32), o_ref.astype(np.float32), **tol)
