"""Migration proof #16: mechanical port of the reference test file
``/root/reference/tests/attention/test_batch_attention.py`` (the
``test_batch_attention_correctness`` matrix) run against
``flashinfer_tpu``.

Same porting contract as tests/test_ported_batch_prefill.py: the
reference's self-consistency oracle is kept — the OLD scheduler
(``BatchPrefillWithPagedKVCacheWrapper.run(..., return_lse=True,
v_scale=)``) vs the NEW holistic ``BatchAttention`` (reference
_core.py contract: 9-positional plan with BOTH head dims, run always
returning ``(out, lse)`` with per-run ``v_scale``/``logits_soft_cap``)
— plus a direct f64 oracle so the pair cannot agree on a shared bug.

Drops (documented): the reference's noncontiguous-q test exercises
torch stride semantics (jnp arrays are always logically contiguous);
its SM120 xfail is CUDA-arch bookkeeping.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import flashinfer_tpu as fi
from tests.test_ported_batch_prefill import FULL, _sample, _work_gate

_WORK_CAP = 2 ** 31


def _sample_sparse(kind, *param_lists, specials=(), factor=10):
    """Second-level deterministic subsample: this file's base matrix is
    ~23k cells (10 seq configs x 2304 combos) and every cell runs THREE
    implementations over multi-hundred-request batches — _sample's 1/48
    stride alone still keeps 480 cells.  Same stable-hash ranking as
    _sample, same specials re-pinning."""
    import hashlib

    cases = _sample(kind, *param_lists, specials=specials)
    if FULL:
        return cases

    def case_hash(c):
        stable = tuple(getattr(x, "__name__", x) for x in (kind,) + c)
        return int.from_bytes(
            hashlib.md5(repr(stable).encode()).digest()[:8], "little")

    keep = sorted(cases, key=case_hash)[:max(1, len(cases) // factor)]
    for idx, val in specials:
        if not any(c[idx] == val for c in keep):
            keep.append(next(c for c in cases if c[idx] == val))
    return keep


def _seq_len_configs():
    """Reference _build_seq_len_configs (test_batch_attention.py:56) —
    the fixed configs; the 256-request random config is kept under its
    own deterministic rng."""
    np.random.seed(42)
    cfgs = [
        [(146, 146)],
        [(67, 67)],
        [(8190, 7939)],
        [(2048, 1)] * 77,
        [(4099, 129)] * 2,
        [(600, 1)] * 132 * 2 + [(5000, 3)] * 128,
        [(1024, 1)] * 100 + [(8192, 17)] * 8,
        [(766, 2)] * 99 + [(1024, 512)] * 1,
        [(2, 235)] + [(1, 13353)],
    ]
    bsz, stride, sparsity = 256, 16, 0.05
    full_kv_len = np.random.randint(1000, 11000, size=bsz)
    seq = []
    for i in range(bsz):
        if i % stride == 0:
            seq.append((int(full_kv_len[i]), stride + 1))
        else:
            seq.append((int(full_kv_len[i] * sparsity), 1))
    cfgs.append(seq)
    return cfgs


def _oracle(q, kc, vc, qo_indptr, kv_indptr, kv_indices, kv_lens, PS,
            layout, causal, sm_scale, soft_cap, v_scale):
    """Independent f64 per-request oracle (bottom-right causal, tanh
    soft-cap, v_scale on the output)."""
    kcn = np.asarray(kc, np.float64)
    vcn = np.asarray(vc, np.float64)
    if layout == "HND":
        kcn = kcn.transpose(0, 2, 1, 3)
        vcn = vcn.transpose(0, 2, 1, 3)
    rows = kcn.reshape(-1, kcn.shape[2], kcn.shape[3])
    vrows = vcn.reshape(-1, vcn.shape[2], vcn.shape[3])
    qn = np.asarray(q, np.float64)
    group = qn.shape[1] // rows.shape[1]
    outs = []
    for r in range(len(kv_lens)):
        qs, qe = qo_indptr[r], qo_indptr[r + 1]
        pages = kv_indices[kv_indptr[r]:kv_indptr[r + 1]]
        tok = np.arange(kv_lens[r])
        rr = pages[tok // PS] * PS + tok % PS
        ki = np.repeat(rows[rr], group, axis=1)
        vi = np.repeat(vrows[rr], group, axis=1)
        qi = qn[qs:qe]
        s = np.einsum("qhd,khd->hqk", qi, ki) * sm_scale
        if soft_cap > 0:
            s = soft_cap * np.tanh(s / soft_cap)
        if causal:
            qo_len, kv_len = qi.shape[0], ki.shape[0]
            mask = (kv_len - qo_len + np.arange(qo_len)[:, None]
                    >= np.arange(kv_len)[None, :])
            s = np.where(mask[None], s, -np.inf)
        m = s.max(-1, keepdims=True)
        m = np.where(np.isfinite(m), m, 0.0)
        e = np.exp(s - m)
        denom = e.sum(-1, keepdims=True)
        # fully-masked rows (causal with qo_len > kv_len, as in config 8's
        # (2, 235) request) produce zero output, matching both wrappers
        p = e / np.where(denom > 0, denom, 1.0)
        outs.append(np.einsum("hqk,khd->qhd", p, vi))
    o = np.concatenate(outs, 0)
    if v_scale is not None:
        o = o * v_scale
    return o


@pytest.mark.parametrize(
    "cfg_idx,page_block_size,num_kv_heads,gqa_group_size,head_dim,"
    "v_scale,causal,layout,test_dtype,logits_soft_cap",
    _sample_sparse(
        "batch_attention",
        list(range(10)), [1, 8, 16], [1, 4], [1, 4, 7, 8],
        [64, 128, 256], [2.0, None], [False, True], ["HND", "NHD"],
        [jnp.bfloat16, jnp.float16], [0.0, 50.0],
        # pin a v_scale cell, a soft-cap cell, and a gqa=7 cell
        specials=((5, 2.0), (9, 50.0), (3, 7)),
    ),
)
def test_batch_attention_correctness(cfg_idx, page_block_size,
                                     num_kv_heads, gqa_group_size,
                                     head_dim, v_scale, causal, layout,
                                     test_dtype, logits_soft_cap):
    """Reference test_batch_attention_correctness
    (test_batch_attention.py:261): old scheduler vs holistic
    BatchAttention, plus an independent oracle."""
    pairs = _seq_len_configs()[cfg_idx]
    kv_lens = np.array([p[0] for p in pairs], np.int64)
    qo_lens = np.array([p[1] for p in pairs], np.int64)
    num_qo_heads = num_kv_heads * gqa_group_size
    # the CPU xla fallback materializes the padded DENSE
    # [total_q, total_kv] score matrix across the whole batch (the
    # Pallas kernels tile it on TPU), so the CI gate must use that
    # cost, not the per-request sum
    def _pow2(n):
        return 1 << (int(n) - 1).bit_length()
    dense = (_pow2(max(int(qo_lens.sum()), 128))
             * _pow2(max(int(kv_lens.sum()), 128))
             * num_qo_heads * head_dim)
    if not FULL and dense > _WORK_CAP:
        pytest.skip(
            f"dense xla-fallback work {dense:.1e} exceeds the CPU CI "
            f"cap {_WORK_CAP:.1e}; FLASHINFER_TPU_FULL_MATRIX run "
            "(TPU kernels tile this shape)")
    PS = page_block_size
    pages_per = -(-kv_lens // PS)
    q_indptr = np.concatenate([[0], np.cumsum(qo_lens)]).astype(np.int32)
    kv_indptr = np.concatenate([[0], np.cumsum(pages_per)]).astype(np.int32)
    num_blocks = int(kv_indptr[-1])
    key = jax.random.PRNGKey(0)
    q = jax.random.uniform(
        key, (int(q_indptr[-1]), num_qo_heads, head_dim), test_dtype)
    kv_shape = ((num_blocks, 2, PS, num_kv_heads, head_dim)
                if layout == "NHD"
                else (num_blocks, 2, num_kv_heads, PS, head_dim))
    kv_data = jax.random.normal(jax.random.fold_in(key, 1), kv_shape,
                                test_dtype)
    kv_indices = np.arange(num_blocks, dtype=np.int32)
    last_page_len = ((kv_lens - 1) % PS + 1).astype(np.int32)

    # --------- old scheduler --------- #
    wrapper_old = fi.BatchPrefillWithPagedKVCacheWrapper(
        jnp.empty(1024, jnp.uint8), kv_layout=layout, backend="fa2")
    wrapper_old.plan(
        q_indptr, kv_indptr, kv_indices, last_page_len, num_qo_heads,
        num_kv_heads, head_dim, PS, causal=causal,
        q_data_type=test_dtype, kv_data_type=test_dtype,
        logits_soft_cap=logits_soft_cap)
    out_old, lse_old = wrapper_old.run(
        q, kv_data, return_lse=True, v_scale=v_scale)

    # --------- holistic scheduler --------- #
    wrapper = fi.BatchAttention(kv_layout=layout)
    wrapper.plan(
        q_indptr, kv_indptr, kv_indices, kv_lens.astype(np.int32),
        num_qo_heads, num_kv_heads, head_dim, head_dim, PS,
        causal=causal, q_data_type=test_dtype, kv_data_type=test_dtype,
        logits_soft_cap=logits_soft_cap)
    out_new, lse_new = wrapper.run(
        q, kv_data, v_scale=v_scale, logits_soft_cap=logits_soft_cap)

    np.testing.assert_allclose(
        np.asarray(out_old, np.float32), np.asarray(out_new, np.float32),
        rtol=1e-2, atol=1e-2)
    np.testing.assert_allclose(
        np.asarray(lse_old, np.float32), np.asarray(lse_new, np.float32),
        rtol=1e-2, atol=1e-2)

    # --------- independent oracle (beyond the reference's pair) -------- #
    sm_scale = 1.0 / float(np.sqrt(head_dim))
    kc = kv_data[:, 0]
    vc = kv_data[:, 1]
    o_ref = _oracle(q, kc, vc, q_indptr, kv_indptr, kv_indices, kv_lens,
                    PS, layout, causal, sm_scale, logits_soft_cap, v_scale)
    np.testing.assert_allclose(
        np.asarray(out_new, np.float32), o_ref.astype(np.float32),
        rtol=2e-2, atol=2e-2)
