"""Migration proof #19: mechanical port of the reference test file
``/root/reference/tests/utils/test_quantization.py`` (packbits /
segment_packbits vs numpy.packbits), matrices verbatim, torch -> jnp.
The 999999-element cell runs (bit-packing is cheap on CPU)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import flashinfer_tpu as fi
from tests.test_ported_batch_prefill import _sample


@pytest.mark.parametrize(
    "num_elements,bitorder",
    _sample(
        "packbits",
        [1, 10, 99, 128, 999, 5000, 131072, 999999], ["big", "little"],
        specials=((0, 999999), (1, "little")),
    ),
)
def test_packbits(num_elements, bitorder):
    """Reference test_packbits (test_quantization.py:33)."""
    x = np.asarray(
        jax.random.uniform(jax.random.PRNGKey(42), (num_elements,))
    ) < 0.5
    ref = np.packbits(x, bitorder=bitorder)
    got = fi.quantization.packbits(jnp.asarray(x), bitorder)
    np.testing.assert_array_equal(np.asarray(got), ref)


@pytest.mark.parametrize(
    "batch_size,bitorder",
    _sample(
        "segment_packbits",
        [1, 10, 99, 128, 777, 999], ["big", "little"],
        specials=((0, 999),),
    ),
)
def test_segment_packbits(batch_size, bitorder):
    """Reference test_segment_packbits (test_quantization.py:60):
    per-segment packing equals packbits of each slice."""
    old_indptr = np.cumsum(np.arange(batch_size + 1)).astype(np.int64)
    num_elements = int(old_indptr[-1])
    x = np.asarray(
        jax.random.uniform(jax.random.PRNGKey(42), (max(num_elements, 1),))
    )[:num_elements] < 0.5
    y, new_indptr = fi.quantization.segment_packbits(
        jnp.asarray(x), jnp.asarray(old_indptr), bitorder)
    y_np, new_np = np.asarray(y), np.asarray(new_indptr)
    for i in range(batch_size):
        seg = x[old_indptr[i]:old_indptr[i + 1]]
        ref = np.asarray(fi.packbits(jnp.asarray(seg), bitorder))
        np.testing.assert_array_equal(
            y_np[new_np[i]:new_np[i + 1]], ref, err_msg=f"segment {i}")
