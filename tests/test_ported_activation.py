"""Migration proof #8: mechanical port of the reference test file
``/root/reference/tests/utils/test_activation.py`` — the gated
activation family on the reference's matrices.  Gate-half convention
matches the reference (act on the FIRST half, multiply the second).
``enable_pdl`` rows run inert; sampled by the shared 1/48 rank sampler
(FULL for all)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from scipy.stats import norm as _scipy_norm  # exact-erf gelu oracle

import flashinfer_tpu as fi
from tests.test_ported_batch_prefill import _sample

_MATRIX = ([128, 256, 512, 2048, 4096, 11008, 16384],
           [1, 2, 4, 8, 16], [1, 2, 4, 8, 16, 32, 64, 128, 512],
           [True, False])


def _x(dim, batch_size, seq_len, seed):
    return jax.random.normal(
        jax.random.PRNGKey(seed), (batch_size, seq_len, 2 * dim),
        jnp.float16)


@pytest.mark.parametrize(
    "dim,batch_size,seq_len,enable_pdl", _sample("silu", *_MATRIX))
def test_fused_silu_mul(dim, batch_size, seq_len, enable_pdl):
    x = _x(dim, batch_size, seq_len, 0)
    xf = np.asarray(x, np.float32)
    y_ref = xf[..., dim:] * (xf[..., :dim] /
                             (1 + np.exp(-xf[..., :dim])))
    y = fi.silu_and_mul(x, enable_pdl=enable_pdl)
    np.testing.assert_allclose(np.asarray(y, np.float32), y_ref,
                               rtol=1e-2, atol=1e-2)


@pytest.mark.parametrize(
    "dim,batch_size,seq_len,enable_pdl", _sample("gelu_tanh", *_MATRIX))
def test_fused_gelu_tanh_mul(dim, batch_size, seq_len, enable_pdl):
    x = _x(dim, batch_size, seq_len, 1)
    xf = np.asarray(x, np.float32)
    g = xf[..., :dim]
    inner = np.sqrt(2 / np.pi) * (g + 0.044715 * g ** 3)
    y_ref = xf[..., dim:] * (0.5 * g * (1 + np.tanh(inner)))
    y = fi.gelu_tanh_and_mul(x, enable_pdl=enable_pdl)
    np.testing.assert_allclose(np.asarray(y, np.float32), y_ref,
                               rtol=1e-2, atol=1e-2)


@pytest.mark.parametrize(
    "dim,batch_size,seq_len,enable_pdl", _sample("gelu", *_MATRIX))
def test_fused_gelu_mul(dim, batch_size, seq_len, enable_pdl):
    x = _x(dim, batch_size, seq_len, 2)
    xf = np.asarray(x, np.float32)
    g = xf[..., :dim].astype(np.float64)
    y_ref = xf[..., dim:].astype(np.float64) * g * _scipy_norm.cdf(g)
    y = fi.gelu_and_mul(x, enable_pdl=enable_pdl)
    np.testing.assert_allclose(np.asarray(y, np.float64), y_ref,
                               rtol=1e-2, atol=1e-2)


def test_out_rejected():
    x = _x(128, 1, 1, 3)
    with pytest.raises(ValueError, match="out="):
        fi.silu_and_mul(x, out=jnp.empty((1, 1, 128), jnp.float16))
