"""BatchAttention (holistic mixed batch), POD alias, attention sinks, and
native-planner parity tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import flashinfer_tpu as fi
from flashinfer_tpu.testing import attention_ref


def _mixed_setup(seed=0):
    """3 requests: 1-token decode, 16-token prefill-append, 1-token decode."""
    HQ, HKV, D, PS = 4, 2, 64, 8
    qo_lens = [1, 16, 1]
    kv_lens = [40, 32, 9]
    num_pages = 32
    rng = np.random.default_rng(seed)
    pages_per = [-(-l // PS) for l in kv_lens]
    kv_indptr = np.concatenate([[0], np.cumsum(pages_per)]).astype(np.int32)
    indices = rng.permutation(num_pages)[: kv_indptr[-1]].astype(np.int32)
    qo_indptr = np.concatenate([[0], np.cumsum(qo_lens)]).astype(np.int32)
    kc = jax.random.normal(jax.random.PRNGKey(seed), (num_pages, PS, HKV, D), jnp.float32)
    vc = jax.random.normal(jax.random.PRNGKey(seed + 1), (num_pages, PS, HKV, D), jnp.float32)
    q = jax.random.normal(jax.random.PRNGKey(seed + 2), (int(qo_indptr[-1]), HQ, D), jnp.float32)
    return (HQ, HKV, D, PS, qo_lens, kv_lens, qo_indptr, kv_indptr, indices,
            kc, vc, q)


def _ref_per_request(q, kc, vc, qo_indptr, kv_indptr, indices, kv_lens, PS,
                     causal=True):
    rows = np.asarray(kc).reshape(-1, kc.shape[2], kc.shape[3])
    vrows = np.asarray(vc).reshape(-1, vc.shape[2], vc.shape[3])
    outs = []
    for r in range(len(kv_lens)):
        qs, qe = qo_indptr[r], qo_indptr[r + 1]
        pages = indices[kv_indptr[r] : kv_indptr[r + 1]]
        tok = np.arange(kv_lens[r])
        rr = pages[tok // PS] * PS + tok % PS
        outs.append(
            attention_ref(q[qs:qe], jnp.asarray(rows[rr]), jnp.asarray(vrows[rr]),
                          causal=causal)
        )
    return jnp.concatenate(outs)


@pytest.mark.parametrize("cls", [fi.BatchAttention, fi.PODWithPagedKVCacheWrapper])
def test_holistic_mixed_batch(cls):
    (HQ, HKV, D, PS, qo_lens, kv_lens, qo_indptr, kv_indptr, indices,
     kc, vc, q) = _mixed_setup()
    w = cls(kv_layout="NHD")
    w.plan(qo_indptr, kv_indptr, indices, np.array(kv_lens), HQ, HKV, D, D,
           PS, causal=True)
    res = w.run(q, (kc, vc))
    # reference contracts differ: BatchAttention.run ALWAYS returns
    # (out, lse) (_core.py:216); the POD alias returns the output
    out = res[0] if isinstance(res, tuple) else res
    ref = _ref_per_request(q, kc, vc, qo_indptr, kv_indptr, indices, kv_lens, PS)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3)


def test_attention_sink_epilogue():
    """sink == -inf must be a no-op; large sink shrinks the output."""
    out = jax.random.normal(jax.random.PRNGKey(0), (8, 4, 32))
    lse = jax.random.normal(jax.random.PRNGKey(1), (8, 4))
    no_sink = fi.apply_attention_sink(out, lse, jnp.full((4,), -1e30))
    np.testing.assert_allclose(np.asarray(no_sink), np.asarray(out), rtol=1e-5, atol=1e-6)
    big_sink = fi.apply_attention_sink(out, lse, jnp.full((4,), 50.0))
    assert float(jnp.max(jnp.abs(big_sink))) < 1e-6
    # exact math: scale = exp(lse) / (exp(lse) + exp(s))
    s = jnp.array([0.5, -1.0, 2.0, 0.0])
    got = fi.apply_attention_sink(out, lse, s)
    scale = np.exp(np.asarray(lse)) / (np.exp(np.asarray(lse)) + np.exp(np.asarray(s))[None])
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(out) * scale[..., None], rtol=1e-4, atol=1e-5
    )


def test_sink_wrapper():
    (HQ, HKV, D, PS, qo_lens, kv_lens, qo_indptr, kv_indptr, indices,
     kc, vc, q) = _mixed_setup(3)
    sink = jnp.array([0.0, 1.0, -2.0, 0.5])
    w = fi.BatchAttentionWithAttentionSinkWrapper(kv_layout="NHD", sink=sink)
    # reference signature: the sink wrapper derives from the PAGED PREFILL
    # wrapper, so plan's 4th positional is last_page_len (attention/
    # _core.py:330 ctor -> BatchPrefillWithPagedKVCacheWrapper.plan)
    pages_per_req = np.asarray(kv_indptr[1:]) - np.asarray(kv_indptr[:-1])
    last_page_len = (np.array(kv_lens)
                     - (np.maximum(pages_per_req, 1) - 1) * PS).astype(
                         np.int32)
    w.plan(qo_indptr, kv_indptr, indices, last_page_len, HQ, HKV, D, PS,
           causal=True)
    out = w.run(q, (kc, vc))
    base = fi.BatchAttention(kv_layout="NHD")
    base.plan(qo_indptr, kv_indptr, indices, np.array(kv_lens), HQ, HKV, D,
              D, PS, causal=True)
    o, lse = base.run(q, (kc, vc))
    ref = fi.apply_attention_sink(o, lse, sink)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-5)


def test_native_planner_matches_numpy_fallback():
    from flashinfer_tpu import native

    rng = np.random.default_rng(0)
    indptr = np.array([0, 3, 3, 7], np.int32)
    indices = rng.integers(0, 100, 7).astype(np.int32)
    last = np.array([5, 0, 2], np.int32)
    t1, l1 = native.decode_plan(indptr, indices, last, 16, 8, 8)
    lib_save = native._LIB
    native._LIB = None  # force numpy fallback
    try:
        t2, l2 = native.decode_plan(indptr, indices, last, 16, 8, 8)
    finally:
        native._LIB = lib_save
    np.testing.assert_array_equal(t1, t2)
    np.testing.assert_array_equal(l1, l2)

    s1, p1 = native.token_axis_plan(np.array([0, 2, 6]), np.array([4, 0]), 8, -1)
    native._LIB = None
    try:
        s2, p2 = native.token_axis_plan(np.array([0, 2, 6]), np.array([4, 0]), 8, -1)
    finally:
        native._LIB = lib_save
    np.testing.assert_array_equal(s1, s2)
    np.testing.assert_array_equal(p1, p2)

    r1 = native.paged_gather_plan(
        np.array([0, 5, 12]), np.array([0, 1, 3]),
        np.array([4, 0, 2], np.int32), 8, 16,
    )
    native._LIB = None
    try:
        r2 = native.paged_gather_plan(
            np.array([0, 5, 12]), np.array([0, 1, 3]),
            np.array([4, 0, 2], np.int32), 8, 16,
        )
    finally:
        native._LIB = lib_save
    np.testing.assert_array_equal(r1, r2)


def test_native_planner_bounds_errors():
    from flashinfer_tpu import native

    if native.get_lib() is None:
        pytest.skip("native planner not built")
    with pytest.raises(ValueError, match="exceeds buckets"):
        native.decode_plan(
            np.array([0, 20]), np.arange(20, dtype=np.int32),
            np.array([1], np.int32), 16, 8, 8,
        )


def test_native_mask_plan_matches_numpy_fallback():
    """C++ per-unit mask bitmap == the numpy per-tile packbits path, on
    ragged geometry with partial tiles/chunks and a zero-kv request."""
    from flashinfer_tpu import native
    from flashinfer_tpu.ops.paged_prefill import build_prefill_work_units

    rng = np.random.default_rng(3)
    qo_lens = [130, 40, 7, 0, 65]
    kv_lens = np.array([200, 150, 3, 90, 0], np.int64)
    qo_indptr = np.concatenate([[0], np.cumsum(qo_lens)])
    PS, ppc, bq = 16, 4, 64
    pages_per = [max(-(-int(l) // PS), 0) for l in kv_lens]
    kv_page_indptr = np.concatenate([[0], np.cumsum(pages_per)])
    kv_indices = np.arange(int(kv_page_indptr[-1]), dtype=np.int32)
    mask_flat = rng.random(
        int(np.sum(np.asarray(qo_lens) * np.asarray(kv_lens)))
    ) < 0.5

    def build():
        # pack_tiles=False keeps the unit enumeration canonical so the
        # first build actually routes through the C++ planner (packed
        # tiles on this unaligned geometry would force numpy for both
        # sides and compare nothing)
        plan = build_prefill_work_units(
            qo_indptr, kv_page_indptr, kv_indices, kv_lens,
            block_q=bq, pages_per_chunk=ppc, page_size=PS,
            mask_flat=mask_flat, pack_tiles=False,
        )
        return plan["mask_bytes"]

    if native.get_lib() is None:
        pytest.skip("native planner unavailable")
    m_native = build()
    lib_save = native._LIB
    native._LIB = None  # force numpy fallback
    try:
        m_numpy = build()
    finally:
        native._LIB = lib_save
    np.testing.assert_array_equal(m_native, m_numpy)


def test_sink_wrapper_scale_kwargs_no_double_epilogue():
    """v_scale=1.0 must be an identity on the sink wrapper (regression:
    the base run's scale branch recursed VIRTUALLY and applied the sink
    epilogue twice), and per-run k_scale must not stick."""
    (HQ, HKV, D, PS, qo_lens, kv_lens, qo_indptr, kv_indptr, indices,
     kc, vc, q) = _mixed_setup(5)
    sink = jnp.array([0.3, -0.5, 1.0, 0.0])
    pages_per_req = np.asarray(kv_indptr[1:]) - np.asarray(kv_indptr[:-1])
    last_page_len = (np.array(kv_lens)
                     - (np.maximum(pages_per_req, 1) - 1) * PS).astype(
                         np.int32)
    w = fi.BatchAttentionWithAttentionSinkWrapper(kv_layout="NHD", sink=sink)
    w.plan(qo_indptr, kv_indptr, indices, last_page_len, HQ, HKV, D, PS,
           causal=True)
    plain = w.run(q, (kc, vc))
    with_vs1 = w.run(q, (kc, vc), v_scale=1.0)
    np.testing.assert_allclose(
        np.asarray(with_vs1), np.asarray(plain), rtol=0, atol=0)
    # k_scale applies per call and does not stick
    scaled = w.run(q, (kc, vc), k_scale=0.5)
    assert float(np.abs(np.asarray(scaled) - np.asarray(plain)).max()) > 1e-4
    again = w.run(q, (kc, vc))
    np.testing.assert_allclose(
        np.asarray(again), np.asarray(plain), rtol=0, atol=0)
    # BatchAttention per-run sinks kwarg reaches the base epilogue once
    base = fi.BatchAttention(kv_layout="NHD")
    base.plan(qo_indptr, kv_indptr, indices, np.array(kv_lens), HQ, HKV, D,
              D, PS, causal=True)
    o_s, _ = base.run(q, (kc, vc), sinks=sink)
    np.testing.assert_allclose(
        np.asarray(o_s), np.asarray(plain), rtol=1e-5, atol=1e-6)
