"""flashinfer_tpu.obs — unified runtime observability (ISSUE 2).

Covers the metrics registry + exporters, the ``@flashinfer_api``
instrumentation (including the two satellite regression tests: the
zero-overhead fast path and the trace-apply/timeline interaction), the
plan-lifecycle wiring, profiler thread-safety, the bench row-quality
auditor, and the ``python -m flashinfer_tpu.obs report`` acceptance
criterion (per-op counters + plan-lifecycle metrics after a
tier-1-sized run).
"""

import importlib.util
import json
import os
import subprocess
import sys
import threading
import time

import pytest

from flashinfer_tpu import obs
from flashinfer_tpu.obs import bench_audit, export
from flashinfer_tpu.obs.registry import Histogram, Registry

REPO_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__),
                                         os.pardir))


@pytest.fixture()
def metrics_on(monkeypatch):
    monkeypatch.setenv("FLASHINFER_TPU_METRICS", "1")
    obs.reset()
    yield
    obs.reset()


@pytest.fixture()
def all_obs_off(monkeypatch):
    for var in ("FLASHINFER_TPU_METRICS", "FLASHINFER_TPU_LOGLEVEL",
                "FLASHINFER_TPU_TRACE_DUMP", "FLASHINFER_TPU_TRACE_APPLY"):
        monkeypatch.delenv(var, raising=False)


# ---------------------------------------------------------------- registry --


@pytest.mark.quick
def test_registry_counter_gauge_histogram(metrics_on):
    reg = Registry()
    assert reg.counter_inc("c", op="a") == 1
    assert reg.counter_inc("c", 2, op="a") == 3
    assert reg.counter_inc("c", op="b") == 1
    reg.gauge_set("g", 4.5)
    for v in (5, 15, 150, 1500):
        reg.observe("h", v, op="a")
    snap = reg.snapshot()
    assert snap["counters"]["c"]["{op=a}"] == 3
    assert snap["counters"]["c"]["{op=b}"] == 1
    assert snap["gauges"]["g"][""] == 4.5
    h = snap["histograms"]["h"]["{op=a}"]
    assert h["count"] == 4 and h["min"] == 5 and h["max"] == 1500
    assert 5 <= h["p50"] <= 150  # interpolated, clamped to [min, max]
    assert h["p99"] <= 1500


def test_histogram_quantiles_clamped_and_monotone():
    h = Histogram((1.0, 10.0, 100.0))
    for v in (2, 3, 4, 50):
        h.observe(v)
    q50, q90, q99 = h.quantile(0.5), h.quantile(0.9), h.quantile(0.99)
    assert 2 <= q50 <= 50 and q50 <= q90 <= q99 <= 50
    assert Histogram((1.0,)).quantile(0.5) is None  # empty


def test_registry_thread_safety_counts_exact():
    reg = Registry()
    N, K = 8, 500

    def work():
        for _ in range(K):
            reg.counter_inc("c")
            reg.observe("h", 1.0)

    threads = [threading.Thread(target=work) for _ in range(N)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = reg.snapshot()
    assert snap["counters"]["c"][""] == N * K
    assert snap["histograms"]["h"][""]["count"] == N * K


def test_gate_off_is_noop(monkeypatch):
    monkeypatch.setenv("FLASHINFER_TPU_METRICS", "0")
    obs.reset()
    assert obs.counter_inc("c") == 0
    obs.observe("h", 1.0)
    snap = obs.snapshot()
    assert snap["counters"] == {} and snap["histograms"] == {}


# --------------------------------------------------------------- exporters --


def _sample_snapshot():
    reg = Registry()
    reg.counter_inc("api.calls", 3, op="rmsnorm")
    reg.observe("api.dispatch_us", 42.0, op="rmsnorm")
    reg.gauge_set("g", 1.0)
    return reg.snapshot()


def test_prometheus_format():
    text = export.to_prometheus(_sample_snapshot())
    assert 'flashinfer_tpu_api_calls_total{op="rmsnorm"} 3' in text
    assert "# TYPE flashinfer_tpu_api_dispatch_us histogram" in text
    assert 'le="+Inf"' in text
    assert 'flashinfer_tpu_api_dispatch_us_count{op="rmsnorm"} 1' in text
    assert "# HELP flashinfer_tpu_api_calls" in text  # catalog help wired


def test_chrome_trace_merges_timeline_and_snapshot():
    events = [{"name": "rmsnorm", "ts": 1.0, "dur": 0.001}]
    trace = export.to_chrome_trace(_sample_snapshot(), events)
    spans = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    metas = [e for e in trace["traceEvents"] if e.get("ph") == "M"]
    assert spans[0]["name"] == "rmsnorm" and spans[0]["dur"] == 1000.0
    assert metas and "api.calls" in \
        metas[0]["args"]["snapshot"]["counters"]


# ------------------------------------------------- @flashinfer_api metrics --


def test_api_decorator_records_per_op_metrics(metrics_on):
    from flashinfer_tpu.api_logging import flashinfer_api

    @flashinfer_api(name="obs_unit_op")
    def op(x):
        return x * 2

    for i in range(3):
        assert op(i) == 2 * i
    snap = obs.snapshot()
    assert snap["counters"]["api.calls"]["{op=obs_unit_op}"] == 3
    assert snap["counters"]["api.calls_total"][""] == 3
    assert snap["histograms"]["api.dispatch_us"]["{op=obs_unit_op}"][
        "count"] == 3
    assert op.__flashinfer_api_name__ == "obs_unit_op"


def test_zero_overhead_fast_path(all_obs_off):
    """Satellite: with metrics, logging, trace, and timeline ALL
    disabled, a decorated op hits the SINGLE fast-path branch — one
    `_instrumentation_active` check, then the plain call; the slow path
    must not run (asserted via call-count on stubs, not wall-clock), so
    the disabled path can never quietly grow per-call work."""
    from flashinfer_tpu import api_logging, profiler

    assert not profiler.timeline_active()
    assert api_logging._instrumentation_active() is False

    checks = []
    monkey_active = lambda: (checks.append(1), False)[1]
    bomb = lambda *a, **k: (_ for _ in ()).throw(
        AssertionError("slow path ran with all surfaces disabled"))
    orig_active = api_logging._instrumentation_active
    orig_slow = api_logging._instrumented_call
    api_logging._instrumentation_active = monkey_active
    api_logging._instrumented_call = bomb
    try:
        inner = []

        @api_logging.flashinfer_api
        def op(x):
            inner.append(x)
            return x + 1

        assert op(41) == 42
        assert op(1) == 2
    finally:
        api_logging._instrumentation_active = orig_active
        api_logging._instrumented_call = orig_slow
    assert inner == [41, 1]
    assert len(checks) == 2  # exactly one branch check per call


def test_trace_apply_with_timeline_records_substituted_span(
        monkeypatch, metrics_on):
    """Satellite: with FLASHINFER_TPU_TRACE_APPLY=1 AND an active
    timeline, the recorded span covers the SUBSTITUTED solution — the
    'profiled run executes the SAME configuration' contract in
    api_logging was previously untested."""
    from flashinfer_tpu import profiler, trace
    from flashinfer_tpu.api_logging import flashinfer_api

    monkeypatch.setenv("FLASHINFER_TPU_TRACE_APPLY", "1")
    trace.clear_solutions()

    @flashinfer_api(name="obs_sub_op")
    def op(x, mode="a"):
        return ("default", x)

    def sub(x, mode="a"):
        time.sleep(0.005)
        return ("substituted", x)

    trace.register_solution("obs_sub_op", {"mode": "b"}, sub)
    profiler.start_timeline()
    try:
        out_sub = op(1, mode="b")
        out_def = op(1, mode="a")
    finally:
        events = profiler.stop_timeline()
        trace.clear_solutions()
    assert out_sub == ("substituted", 1)
    assert out_def == ("default", 1)
    spans = [e for e in events if e["name"] == "obs_sub_op"]
    assert len(spans) == 2
    # the first span wraps the substitute, so its duration must cover
    # the substitute's 5 ms sleep
    assert spans[0]["dur"] >= 0.004
    snap = obs.snapshot()
    assert snap["counters"]["trace.solution_hits"]["{op=obs_sub_op}"] == 1
    assert snap["counters"]["trace.solution_misses"]["{op=obs_sub_op}"] == 1


def test_traced_api_counts_hits_and_misses(monkeypatch, metrics_on):
    from flashinfer_tpu import trace

    monkeypatch.setenv("FLASHINFER_TPU_TRACE_APPLY", "1")
    trace.clear_solutions()

    @trace.traced_api(name="obs_traced_op")
    def op(x):
        return x

    trace.register_solution("obs_traced_op", {"arg0": 7}, lambda x: -x)
    assert op(7) == -7
    assert op(8) == 8
    trace.clear_solutions()
    snap = obs.snapshot()
    assert snap["counters"]["trace.solution_hits"]["{op=obs_traced_op}"] == 1
    assert snap["counters"]["trace.solution_misses"][
        "{op=obs_traced_op}"] == 1


# ------------------------------------------------- plan lifecycle metrics --


def test_decode_plan_lifecycle_metrics(metrics_on):
    import numpy as np

    import flashinfer_tpu as fi

    w = fi.BatchDecodeWithPagedKVCacheWrapper(kv_layout="NHD")
    indptr = np.array([0, 2, 4], np.int32)
    indices = np.arange(4, dtype=np.int32)
    last = np.array([4, 4], np.int32)
    w.plan(indptr, indices, last, 4, 2, 64, 4)
    w.plan(indptr, indices, last, 4, 2, 64, 4)  # re-plan
    snap = obs.snapshot()
    name = "BatchDecodeWithPagedKVCacheWrapper"
    assert snap["counters"]["plan.calls"]["{wrapper=%s}" % name] == 2
    assert snap["counters"]["plan.replans"]["{wrapper=%s}" % name] == 1
    waste = snap["histograms"]["plan.padding_waste_pct"]
    # batch 2 pads to 8 (75% waste), 4 pages pad to 8x8=64 slots
    batch_h = waste["{axis=batch,wrapper=%s}" % name]
    assert batch_h["count"] == 2 and abs(batch_h["max"] - 75.0) < 1e-6
    pages_h = waste["{axis=pages,wrapper=%s}" % name]
    assert abs(pages_h["max"] - 100.0 * (1 - 4 / 64)) < 1e-6


def test_prefill_plan_and_sm_scale_rebind_metrics(metrics_on):
    import numpy as np

    import flashinfer_tpu as fi

    w = fi.BatchPrefillWithPagedKVCacheWrapper(kv_layout="NHD")
    w.plan(np.array([0, 2, 4], np.int32), np.array([0, 2, 4], np.int32),
           np.arange(4, dtype=np.int32), np.array([4, 4], np.int32),
           4, 2, 64, 4, causal=True)
    restore = w._rebind_sm_scale(absolute=0.5)
    assert restore is not None
    w._plan = restore
    snap = obs.snapshot()
    name = "BatchPrefillWithPagedKVCacheWrapper"
    assert snap["counters"]["plan.calls"]["{wrapper=%s}" % name] == 1
    assert snap["counters"]["plan.sm_scale_rebinds"][
        "{wrapper=%s}" % name] == 1
    waste = snap["histograms"]["plan.padding_waste_pct"]
    # 4 q tokens pad to 128
    q_h = waste["{axis=q_tokens,wrapper=%s}" % name]
    assert abs(q_h["max"] - 100.0 * (1 - 4 / 128)) < 1e-6


# ------------------------------------------------ profiler thread-safety --


def test_profiler_concurrent_record_and_stop():
    """Satellite: record_event/stop_timeline share a lock — a stop
    mid-stream must neither crash a concurrent recorder nor let a
    second stop double-export."""
    from flashinfer_tpu import profiler

    profiler.start_timeline()
    stop_events = []
    errors = []

    def recorder():
        try:
            for i in range(2000):
                profiler.record_event("op", float(i), float(i) + 0.5)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    def stopper():
        time.sleep(0.002)
        stop_events.append(profiler.stop_timeline())

    threads = [threading.Thread(target=recorder) for _ in range(4)]
    ts = threading.Thread(target=stopper)
    for t in threads:
        t.start()
    ts.start()
    for t in threads + [ts]:
        t.join()
    assert errors == []
    assert profiler.stop_timeline() == []  # concurrent-stop guard
    assert not profiler.timeline_active()
    assert all(e["dur"] == 0.5 for e in stop_events[0])


def test_timeline_stop_twice_returns_events_once(tmp_path):
    from flashinfer_tpu import profiler

    profiler.start_timeline()
    profiler.record_event("x", 0.0, 1.0)
    path = str(tmp_path / "t.json")
    events = profiler.stop_timeline(path)
    assert len(events) == 1
    assert profiler.stop_timeline() == []
    trace = json.loads(open(path).read())
    assert trace["traceEvents"][0]["name"] == "x"


# ------------------------------------------------------- bench row audit --


def _row(tbps, **cfg):
    return dict(phase="decode", bs=64, ctx=4096, tbps=tbps, **cfg)


def test_row_auditor_quality_rules():
    a = bench_audit.RowAuditor([_row(0.73)])
    ok = a.stamp(_row(0.70))
    assert ok["quality"] == "ok" and ok["vs_best"] == round(0.70 / 0.73, 3)
    assert a.stamp(_row(0.40))["quality"] == "degraded"
    # the committed <0.35x rule (the 2026-07-31 19x artifact shape)
    assert a.stamp(_row(0.0378))["quality"] == "poison"
    # a different configuration never competes with this one
    other = a.stamp(dict(phase="decode", bs=1, ctx=512, tbps=0.01))
    assert other["quality"] == "ok" and "vs_best" not in other


def test_row_auditor_poison_history_does_not_set_baseline():
    poisoned = _row(10.0)
    poisoned["quality"] = "poison"
    a = bench_audit.RowAuditor([poisoned, _row(0.73)])
    assert a.stamp(_row(0.70))["quality"] == "ok"  # best is 0.73, not 10


def test_row_auditor_latency_only_rows_use_inverse_us():
    a = bench_audit.RowAuditor([])
    a.stamp(dict(phase="topk", backend="xla", k=40, us=1000.0))
    slow = a.stamp(dict(phase="topk", backend="xla", k=40, us=5000.0))
    assert slow["quality"] == "poison"  # 5x slower < 0.35x inverse


def test_row_auditor_never_raises_on_garbage():
    a = bench_audit.RowAuditor([])
    row = {"phase": "x", "tbps": float("nan")}
    a.stamp(row)  # must not raise; stamp may be absent or ok
    assert a.stamp({"phase": "y", "weird": object()}) is not None


def test_load_banked_history_parses_real_bank():
    rows = bench_audit.load_banked_history(
        os.path.join(REPO_ROOT, "BENCH_BANKED.md"))
    assert rows, "committed BENCH_BANKED.md should yield history rows"
    assert any(r.get("phase") == "decode" for r in rows)
    assert bench_audit.load_banked_history("/nonexistent") == []


def test_bench_emit_row_stamps_quality(capsys):
    spec = importlib.util.spec_from_file_location(
        "bench_obs_test", os.path.join(REPO_ROOT, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod._emit_row(phase="qualitytest", variant="a", tbps=1.0)
    mod._emit_row(phase="qualitytest", variant="a", tbps=0.2)
    lines = [l for l in capsys.readouterr().out.splitlines()
             if l.startswith("ROW ")]
    first, second = (json.loads(l[4:]) for l in lines)
    assert first["quality"] == "ok"
    assert second["quality"] == "poison" and second["vs_best"] == 0.2


# ------------------------------------------------------------- moe drops --


def test_record_dropped_tokens_eager_and_tracer(metrics_on):
    import jax
    import jax.numpy as jnp

    obs.record_dropped_tokens(jnp.array([3], jnp.int32), "alltoall")
    # tracers are skipped, not crashed on
    jax.jit(lambda d: obs.record_dropped_tokens(d, "alltoall") or d)(
        jnp.array([5], jnp.int32))
    snap = obs.snapshot()
    assert snap["counters"]["moe.dropped_tokens"]["{dispatch=alltoall}"] == 3

    from flashinfer_tpu import moe_ep

    assert moe_ep.record_dropped_tokens(
        jnp.array([2], jnp.int32), moe_ep.EpAlgorithm.ALLTOALL) == 2
    assert snap != obs.snapshot()


# ------------------------------------------------------------------- CLI --


def test_obs_report_cli_acceptance():
    """THE acceptance criterion: `python -m flashinfer_tpu.obs report`
    emits a JSON snapshot containing per-op counters and plan-lifecycle
    metrics after a tier-1-sized run."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("FLASHINFER_TPU_METRICS", None)
    p = subprocess.run(
        [sys.executable, "-m", "flashinfer_tpu.obs", "report"],
        capture_output=True, text=True, env=env, cwd=REPO_ROOT,
        timeout=300,
    )
    assert p.returncode == 0, p.stderr[-2000:]
    snap = json.loads(p.stdout)
    ops = {k.strip("{}").partition("=")[2]
           for k in snap["counters"]["api.calls"]}
    assert {"rmsnorm", "silu_and_mul", "sampling_from_probs",
            "single_prefill_with_kv_cache"} <= ops
    assert snap["counters"]["plan.calls"]
    assert any(v >= 1 for v in snap["counters"]["plan.replans"].values())
    assert "plan.padding_waste_pct" in snap["histograms"]
    assert "api.dispatch_us" in snap["histograms"]


def test_obs_doctor_cli():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.run(
        [sys.executable, "-m", "flashinfer_tpu.obs", "doctor"],
        capture_output=True, text=True, env=env, cwd=REPO_ROOT,
        timeout=300,
    )
    assert p.returncode == 0, p.stderr[-2000:]
    report = json.loads(p.stdout)
    assert {"env", "flags", "quarantine", "registry", "lint"} \
        <= set(report)
    assert report["env"].get("flashinfer_tpu")
    assert "FLASHINFER_TPU_METRICS" in report["flags"]
    # lint hygiene: reasonless suppressions are L000/W000 — the tree
    # cannot pass the analyzer with a non-zero count, so doctor must
    # report zero here (and a total, so drift is visible)
    assert report["lint"]["reasonless_suppressions"] == 0
    assert report["lint"]["suppressions"] >= 1
    # cost-model coverage (ISSUE 5): a decorated public op with no
    # roofline formula would bench but never attribute — the doctor
    # must report the straggler list, and the tree must keep it empty
    assert report["costmodel"]["uncovered_api_ops"] == []
    assert report["costmodel"]["api_ops_covered"] >= 10
    assert report["costmodel"]["chip"] in ("v4", "v5e", "v5p", "v6e")


@pytest.mark.slow
def test_serving_phase_emits_decomposition_cpu_dryrun():
    """Schema + wiring of the serving-loop phase decomposition, CPU
    dryrun (values meaningless off-chip; the e2e ROW must carry
    overhead_decomposition with the named phases + residual, and every
    row a quality stamp)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_SMALL="1")
    p = subprocess.run(
        [sys.executable, "bench.py", "--phase", "serving"],
        capture_output=True, text=True, env=env, cwd=REPO_ROOT,
        timeout=560,
    )
    assert p.returncode == 0, p.stderr[-3000:]
    rows = [json.loads(l[4:]) for l in p.stdout.splitlines()
            if l.startswith("ROW ")]
    assert all("quality" in r for r in rows)
    e2e = [r for r in rows if r.get("mode") == "e2e_measured"]
    assert e2e, rows
    decomp = e2e[0]["overhead_decomposition"]
    assert {"attention_us", "kv_append_us", "moe_or_mlp_us",
            "norm_rope_us", "sampling_us", "lm_head_us",
            "residual_us"} == set(decomp)


# ------------------------------------------------------------ doc parity --


def test_observability_doc_names_every_catalog_metric():
    from flashinfer_tpu.obs.catalog import API_OPS, METRICS

    doc = open(os.path.join(REPO_ROOT, "docs", "observability.md")).read()
    for name in METRICS:
        assert f"`{name}`" in doc or name in doc, \
            f"docs/observability.md missing metric {name}"
    # and the doc is linked from README + migration guide
    assert "docs/observability.md" in open(
        os.path.join(REPO_ROOT, "README.md")).read()
    assert "observability.md" in open(
        os.path.join(REPO_ROOT, "docs", "migration.md")).read()
    assert API_OPS  # non-empty catalog backs L005
