"""Randomized geometry fuzz tests (mirrors reference
tests/moe/test_unified_moe_fuzz.py strategy): many random configs vs the
eager oracles."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import flashinfer_tpu as fi
import flashinfer_tpu.fused_moe as moe
from flashinfer_tpu.testing import attention_ref


@pytest.mark.parametrize("seed", range(6))
def test_fuzz_ragged_prefill_geometries(seed):
    rng = np.random.default_rng(seed)
    batch = int(rng.integers(1, 6))
    qo_lens = rng.integers(1, 70, batch)
    extra = rng.integers(0, 50, batch)
    kv_lens = qo_lens + extra  # kv >= qo (append semantics)
    HQ = int(rng.choice([1, 2, 4, 8]))
    HKV = int(rng.choice([h for h in (1, 2, 4, 8) if HQ % h == 0]))
    D = int(rng.choice([32, 64]))
    causal = bool(rng.integers(0, 2))
    qo_indptr = np.concatenate([[0], np.cumsum(qo_lens)])
    kv_indptr = np.concatenate([[0], np.cumsum(kv_lens)])
    q = jax.random.normal(jax.random.PRNGKey(seed), (int(qo_indptr[-1]), HQ, D))
    k = jax.random.normal(jax.random.PRNGKey(seed + 100), (int(kv_indptr[-1]), HKV, D))
    v = jax.random.normal(jax.random.PRNGKey(seed + 200), (int(kv_indptr[-1]), HKV, D))
    w = fi.BatchPrefillWithRaggedKVCacheWrapper()
    w.plan(qo_indptr, kv_indptr, HQ, HKV, D, causal=causal)
    out = w.run(q, k, v)
    for r in range(batch):
        qs, qe = qo_indptr[r], qo_indptr[r + 1]
        ks, ke = kv_indptr[r], kv_indptr[r + 1]
        ref = attention_ref(q[qs:qe], k[ks:ke], v[ks:ke], causal=causal)
        np.testing.assert_allclose(
            np.asarray(out[qs:qe]), np.asarray(ref), rtol=3e-3, atol=3e-3,
            err_msg=f"seed {seed} req {r} ({qo_lens.tolist()}/{kv_lens.tolist()})",
        )


@pytest.mark.parametrize("seed", range(6))
def test_fuzz_moe_configs(seed):
    rng = np.random.default_rng(seed + 50)
    T = int(rng.integers(1, 33))
    E = int(rng.choice([2, 4, 8, 16]))
    K = int(rng.integers(1, min(E, 4) + 1))
    h = int(rng.choice([16, 32]))
    inter = int(rng.choice([16, 64]))
    x = jnp.asarray(rng.normal(size=(T, h)).astype(np.float32))
    w1 = jnp.asarray(rng.normal(size=(E, h, 2 * inter)).astype(np.float32) * 0.1)
    w2 = jnp.asarray(rng.normal(size=(E, inter, h)).astype(np.float32) * 0.1)
    logits = jnp.asarray(rng.normal(size=(T, E)).astype(np.float32))
    wts, ids = moe.route_renormalize(logits, K)
    out = moe.fused_moe(x, w1, w2, wts, ids, E)
    # eager loop oracle
    ref = np.zeros((T, h), np.float32)
    xn, w1n, w2n = np.asarray(x), np.asarray(w1), np.asarray(w2)
    idn, wtn = np.asarray(ids), np.asarray(wts)
    for t in range(T):
        for j in range(K):
            e = int(idn[t, j])
            hdn = xn[t] @ w1n[e]
            d = hdn.shape[-1] // 2
            a = hdn[:d] / (1 + np.exp(-hdn[:d])) * hdn[d:]
            ref[t] += wtn[t, j] * (a @ w2n[e])
    np.testing.assert_allclose(np.asarray(out), ref, rtol=3e-3, atol=3e-3,
                               err_msg=f"seed {seed} T{T} E{E} K{K}")


@pytest.mark.parametrize("seed", range(4))
def test_fuzz_decode_geometries(seed):
    rng = np.random.default_rng(seed + 99)
    batch = int(rng.integers(1, 9))
    PS = int(rng.choice([1, 8, 16]))
    kv_lens = rng.integers(1, 200, batch)
    HQ, HKV, D = 8, int(rng.choice([1, 2, 8])), 64
    pages_per = -(-kv_lens // PS)
    indptr = np.concatenate([[0], np.cumsum(pages_per)]).astype(np.int32)
    npages = int(indptr[-1]) + 4
    indices = rng.permutation(npages)[: indptr[-1]].astype(np.int32)
    last = (kv_lens - (pages_per - 1) * PS).astype(np.int32)
    kc = jax.random.normal(jax.random.PRNGKey(seed), (npages, PS, HKV, D))
    vc = jax.random.normal(jax.random.PRNGKey(seed + 1), (npages, PS, HKV, D))
    q = jax.random.normal(jax.random.PRNGKey(seed + 2), (batch, HQ, D))
    w = fi.BatchDecodeWithPagedKVCacheWrapper(kv_layout="NHD")
    w.plan(indptr, indices, last, HQ, HKV, D, PS)
    out = w.run(q, (kc, vc))
    rows = np.asarray(kc).reshape(-1, HKV, D)
    vrows = np.asarray(vc).reshape(-1, HKV, D)
    for b in range(batch):
        pages = indices[indptr[b] : indptr[b + 1]]
        tok = np.arange(kv_lens[b])
        rr = pages[tok // PS] * PS + tok % PS
        ref = attention_ref(q[b : b + 1], jnp.asarray(rows[rr]), jnp.asarray(vrows[rr]))
        np.testing.assert_allclose(
            np.asarray(out[b]), np.asarray(ref[0]), rtol=3e-3, atol=3e-3,
            err_msg=f"seed {seed} b{b} kv{kv_lens[b]} ps{PS}",
        )
