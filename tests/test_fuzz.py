"""Randomized geometry fuzz tests (mirrors reference
tests/moe/test_unified_moe_fuzz.py strategy): many random configs vs the
eager oracles."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import flashinfer_tpu as fi
import flashinfer_tpu.fused_moe as moe
from flashinfer_tpu.testing import attention_ref


@pytest.mark.parametrize("seed", range(6))
def test_fuzz_ragged_prefill_geometries(seed):
    rng = np.random.default_rng(seed)
    batch = int(rng.integers(1, 6))
    qo_lens = rng.integers(1, 70, batch)
    extra = rng.integers(0, 50, batch)
    kv_lens = qo_lens + extra  # kv >= qo (append semantics)
    HQ = int(rng.choice([1, 2, 4, 8]))
    HKV = int(rng.choice([h for h in (1, 2, 4, 8) if HQ % h == 0]))
    D = int(rng.choice([32, 64]))
    causal = bool(rng.integers(0, 2))
    qo_indptr = np.concatenate([[0], np.cumsum(qo_lens)])
    kv_indptr = np.concatenate([[0], np.cumsum(kv_lens)])
    q = jax.random.normal(jax.random.PRNGKey(seed), (int(qo_indptr[-1]), HQ, D))
    k = jax.random.normal(jax.random.PRNGKey(seed + 100), (int(kv_indptr[-1]), HKV, D))
    v = jax.random.normal(jax.random.PRNGKey(seed + 200), (int(kv_indptr[-1]), HKV, D))
    w = fi.BatchPrefillWithRaggedKVCacheWrapper()
    w.plan(qo_indptr, kv_indptr, HQ, HKV, D, causal=causal)
    out = w.run(q, k, v)
    for r in range(batch):
        qs, qe = qo_indptr[r], qo_indptr[r + 1]
        ks, ke = kv_indptr[r], kv_indptr[r + 1]
        ref = attention_ref(q[qs:qe], k[ks:ke], v[ks:ke], causal=causal)
        np.testing.assert_allclose(
            np.asarray(out[qs:qe]), np.asarray(ref), rtol=3e-3, atol=3e-3,
            err_msg=f"seed {seed} req {r} ({qo_lens.tolist()}/{kv_lens.tolist()})",
        )


@pytest.mark.parametrize("seed", range(6))
def test_fuzz_moe_configs(seed):
    rng = np.random.default_rng(seed + 50)
    T = int(rng.integers(1, 33))
    E = int(rng.choice([2, 4, 8, 16]))
    K = int(rng.integers(1, min(E, 4) + 1))
    h = int(rng.choice([16, 32]))
    inter = int(rng.choice([16, 64]))
    x = jnp.asarray(rng.normal(size=(T, h)).astype(np.float32))
    w1 = jnp.asarray(rng.normal(size=(E, h, 2 * inter)).astype(np.float32) * 0.1)
    w2 = jnp.asarray(rng.normal(size=(E, inter, h)).astype(np.float32) * 0.1)
    logits = jnp.asarray(rng.normal(size=(T, E)).astype(np.float32))
    wts, ids = moe.route_renormalize(logits, K)
    out = moe.fused_moe(x, w1, w2, wts, ids, E)
    # eager loop oracle
    ref = np.zeros((T, h), np.float32)
    xn, w1n, w2n = np.asarray(x), np.asarray(w1), np.asarray(w2)
    idn, wtn = np.asarray(ids), np.asarray(wts)
    for t in range(T):
        for j in range(K):
            e = int(idn[t, j])
            hdn = xn[t] @ w1n[e]
            d = hdn.shape[-1] // 2
            a = hdn[:d] / (1 + np.exp(-hdn[:d])) * hdn[d:]
            ref[t] += wtn[t, j] * (a @ w2n[e])
    np.testing.assert_allclose(np.asarray(out), ref, rtol=3e-3, atol=3e-3,
                               err_msg=f"seed {seed} T{T} E{E} K{K}")


@pytest.mark.parametrize("seed", range(4))
def test_fuzz_decode_geometries(seed):
    rng = np.random.default_rng(seed + 99)
    batch = int(rng.integers(1, 9))
    PS = int(rng.choice([1, 8, 16]))
    kv_lens = rng.integers(1, 200, batch)
    HQ, HKV, D = 8, int(rng.choice([1, 2, 8])), 64
    pages_per = -(-kv_lens // PS)
    indptr = np.concatenate([[0], np.cumsum(pages_per)]).astype(np.int32)
    npages = int(indptr[-1]) + 4
    indices = rng.permutation(npages)[: indptr[-1]].astype(np.int32)
    last = (kv_lens - (pages_per - 1) * PS).astype(np.int32)
    kc = jax.random.normal(jax.random.PRNGKey(seed), (npages, PS, HKV, D))
    vc = jax.random.normal(jax.random.PRNGKey(seed + 1), (npages, PS, HKV, D))
    q = jax.random.normal(jax.random.PRNGKey(seed + 2), (batch, HQ, D))
    w = fi.BatchDecodeWithPagedKVCacheWrapper(kv_layout="NHD")
    w.plan(indptr, indices, last, HQ, HKV, D, PS)
    out = w.run(q, (kc, vc))
    rows = np.asarray(kc).reshape(-1, HKV, D)
    vrows = np.asarray(vc).reshape(-1, HKV, D)
    for b in range(batch):
        pages = indices[indptr[b] : indptr[b + 1]]
        tok = np.arange(kv_lens[b])
        rr = pages[tok // PS] * PS + tok % PS
        ref = attention_ref(q[b : b + 1], jnp.asarray(rows[rr]), jnp.asarray(vrows[rr]))
        np.testing.assert_allclose(
            np.asarray(out[b]), np.asarray(ref[0]), rtol=3e-3, atol=3e-3,
            err_msg=f"seed {seed} b{b} kv{kv_lens[b]} ps{PS}",
        )


@pytest.mark.parametrize("seed", range(5))
def test_fuzz_moe_gmm_geometries(seed):
    """Random token counts / expert counts / routing skew: the Pallas
    gather-GMM backend must match ragged_dot on every geometry (empty
    experts, single-expert hotspots, non-pow2 T)."""
    rng = np.random.default_rng(100 + seed)
    T = int(rng.integers(1, 300))
    E = int(rng.choice([1, 2, 4, 8, 16]))
    K = int(rng.integers(1, min(E, 4) + 1))
    h = int(rng.choice([128, 256]))
    inter = int(rng.choice([128, 384]))
    x = jnp.asarray(rng.standard_normal((T, h)), jnp.float32)
    w1 = jnp.asarray(rng.standard_normal((E, h, 2 * inter)) / np.sqrt(h))
    w2 = jnp.asarray(rng.standard_normal((E, inter, h)) / np.sqrt(inter))
    # skewed routing: half the seeds dump most tokens on expert 0
    if seed % 2:
        ids = jnp.zeros((T, K), jnp.int32).at[:, 1:].set(
            jnp.asarray(rng.integers(0, E, (T, max(K - 1, 0))), jnp.int32)
        )[:, :K]
    else:
        ids = jnp.asarray(rng.integers(0, E, (T, K)), jnp.int32)
    wts = jnp.asarray(rng.random((T, K)) + 0.1, jnp.float32)
    wts = wts / wts.sum(-1, keepdims=True)
    ref = moe.fused_moe(x, w1, w2, wts, ids, E, backend="ragged")
    out = moe.fused_moe(x, w1, w2, wts, ids, E, backend="gmm")
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3,
        err_msg=f"T={T} E={E} K={K} h={h} inter={inter}",
    )


@pytest.mark.parametrize("seed", range(4))
def test_fuzz_masked_fused_prefill_geometries(seed):
    """Random ragged batches with random custom masks: the in-kernel
    packed-mask fused prefill must match the dense-mask oracle."""
    rng = np.random.default_rng(200 + seed)
    HQ, HKV, D = 4, 2, 32
    PS = int(rng.choice([8, 16]))
    B = int(rng.integers(1, 4))
    qo_lens = rng.integers(1, 200, B)
    kv_lens = np.maximum(rng.integers(1, 300, B), qo_lens)
    qo_indptr = np.concatenate([[0], np.cumsum(qo_lens)])
    pages_per = [-(-int(l) // PS) for l in kv_lens]
    kv_page_indptr = np.concatenate([[0], np.cumsum(pages_per)])
    npages = int(kv_page_indptr[-1])
    kv_indices = rng.permutation(npages).astype(np.int32)

    masks = []
    for q_, k_ in zip(qo_lens, kv_lens):
        m = rng.random((q_, k_)) < 0.5
        qpos = np.arange(q_) + k_ - q_
        m[np.arange(q_), qpos] = True  # keep rows non-empty
        masks.append(m)
    packed = np.packbits(
        np.concatenate([m.reshape(-1) for m in masks]).astype(np.uint8),
        bitorder="little",
    )
    kc = jax.random.normal(
        jax.random.PRNGKey(seed), (npages, HKV, PS, D), jnp.float32
    )
    vc = jax.random.normal(
        jax.random.PRNGKey(seed + 50), (npages, HKV, PS, D), jnp.float32
    )
    q = jax.random.normal(
        jax.random.PRNGKey(seed + 99), (int(qo_indptr[-1]), HQ, D),
        jnp.float32,
    )
    w = fi.BatchPrefillWithPagedKVCacheWrapper(
        kv_layout="HND", backend="pallas_fused"
    )
    w.plan(
        qo_indptr, kv_page_indptr, kv_indices,
        [int(l) - (p - 1) * PS for l, p in zip(kv_lens, pages_per)],
        HQ, HKV, D, PS, causal=True, packed_custom_mask=packed,
    )
    assert "mask_bytes" in w._fused_plan[0]
    out = w.run(q, (kc, vc))
    kflat = np.asarray(jnp.swapaxes(kc, 1, 2)).reshape(-1, HKV, D)
    vflat = np.asarray(jnp.swapaxes(vc, 1, 2)).reshape(-1, HKV, D)
    for r in range(B):
        qs, qe = int(qo_indptr[r]), int(qo_indptr[r + 1])
        rows = np.concatenate([
            np.arange(PS) + p * PS
            for p in kv_indices[kv_page_indptr[r]:kv_page_indptr[r + 1]]
        ])[: kv_lens[r]]
        ref = attention_ref(
            q[qs:qe], jnp.asarray(kflat[rows]), jnp.asarray(vflat[rows]),
            custom_mask=jnp.asarray(masks[r]),
        )
        np.testing.assert_allclose(
            np.asarray(out[qs:qe]), np.asarray(ref), rtol=2e-3, atol=2e-3,
            err_msg=f"seed {seed} request {r}",
        )


@pytest.mark.parametrize("seed", range(5))
def test_fuzz_threshold_topk_distributions(seed):
    """Adversarial value distributions for the bit-space top-k: heavy
    ties, denormal-scale values, mixed magnitudes, +/-inf floors."""
    from flashinfer_tpu import topk

    rng = np.random.default_rng(300 + seed)
    V = int(rng.choice([257, 1024, 4096]))
    k = int(rng.integers(1, min(V, 128)))
    kind = seed % 5
    if kind == 0:  # quantized values: massive tie classes
        scores = rng.integers(-3, 4, (4, V)).astype(np.float32)
    elif kind == 1:  # tiny magnitudes near denormal scale
        scores = (rng.standard_normal((4, V)) * 1e-38).astype(np.float32)
    elif kind == 2:  # huge dynamic range with -inf entries
        scores = rng.standard_normal((4, V)).astype(np.float32)
        scores[:, rng.integers(0, V, V // 4)] = -np.inf
        scores[:, 0] = -1e30
    elif kind == 3:  # all-equal rows
        scores = np.full((4, V), 2.5, np.float32)
    else:  # mixed sign + exact zeros
        scores = np.where(rng.random((4, V)) < 0.5, 0.0,
                          rng.standard_normal((4, V))).astype(np.float32)
    sx = jnp.asarray(scores)
    vx, ix = topk.top_k_values_indices(sx, k, backend="xla")
    vt, it = topk.top_k_values_indices(sx, k, backend="threshold")
    # exact contract: same MULTISET of values (ties may pick different
    # members, but the value profile must match the sort oracle exactly)
    for a, b in zip(np.asarray(vx), np.asarray(vt)):
        af = np.sort(a[np.isfinite(a)])
        bf = np.sort(b[np.isfinite(b)])
        np.testing.assert_array_equal(af, bf, err_msg=f"kind={kind} k={k}")
