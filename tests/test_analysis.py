"""Multi-pass static analyzer (flashinfer_tpu.analysis).

Each pass must flag the EXACT pre-fix ADVICE.md round-5 bug shape it
was built from (true positive), honor reasoned ``# graft-lint: ok``
suppressions (rejecting reasonless ones as L000), and stay quiet on the
fixed/clean shape.  The whole-tree run over ``flashinfer_tpu/`` against
the committed baseline is the tier-1 CI gate: new findings fail the
suite at review time, not at the next advisor round.
"""

import json
import os
import textwrap

import pytest

from flashinfer_tpu import analysis
from flashinfer_tpu.analysis import (alias_rebind, jit_staticness,
                                     obs_coverage, signature_parity,
                                     tuning_schema)
from flashinfer_tpu.analysis.core import Project, load_source

PKG_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "flashinfer_tpu"))


def _project(*named_sources):
    return Project([load_source(textwrap.dedent(src), name)
                    for name, src in named_sources])


# ---------------------------------------------------------------- L001 --

# the ADVICE.md round-5 item-1 shape: the paged base wrapper binds
# `forward = run` at class-definition time; subclasses redefine run
PRE_FIX_ALIAS = """
    class BasePagedWrapper:
        def run(self, q, kv):
            return "base"
        forward = run

    class SinkWrapper(BasePagedWrapper):
        def run(self, q, kv):
            return "base+sink-epilogue"
"""

POST_FIX_ALIAS = PRE_FIX_ALIAS + """\
        forward = run
"""


def test_l001_flags_pre_fix_sink_wrapper_shape():
    findings = alias_rebind.run(_project(("attention.py", PRE_FIX_ALIAS)))
    assert len(findings) == 1, findings
    f = findings[0]
    assert f.code == "L001" and f.func == "SinkWrapper.run"
    assert "forward = run" in f.message and "SinkWrapper" in f.message
    # the runtime truth the lint models: the inherited alias really does
    # call the BASE run
    ns = {}
    exec(textwrap.dedent(PRE_FIX_ALIAS), ns)
    assert ns["SinkWrapper"]().forward(0, 0) == "base"  # the silent bug


def test_l001_rebind_fix_is_clean():
    findings = alias_rebind.run(_project(("attention.py", POST_FIX_ALIAS)))
    assert findings == [], findings
    ns = {}
    exec(textwrap.dedent(POST_FIX_ALIAS), ns)
    assert ns["SinkWrapper"]().forward(0, 0) == "base+sink-epilogue"


def test_l001_resolves_bases_across_files():
    """The real bug spanned prefill.py (alias) and attention.py
    (subclass) — the pass must resolve inheritance project-wide."""
    base = """
        class BasePagedWrapper:
            def run(self, q, kv):
                return "base"
            forward = run
    """
    sub = """
        class BatchAttention(BasePagedWrapper):
            def run(self, q, kv):
                return "holistic"
    """
    findings = alias_rebind.run(
        _project(("prefill.py", base), ("attention.py", sub)))
    assert [f.code for f in findings] == ["L001"]
    assert findings[0].filename == "attention.py"


def test_l001_grandchild_inheriting_redefined_run_flagged():
    """'inheriting a redefined run': the grandchild's forward skips the
    override it actually inherits, even though it defines nothing."""
    src = PRE_FIX_ALIAS + """
    class DerivedOfSink(SinkWrapper):
        pass
    """
    findings = alias_rebind.run(_project(("a.py", src)))
    assert {f.func for f in findings} == {"SinkWrapper.run",
                                          "DerivedOfSink"}


def test_l001_alias_above_def_in_same_class_flagged():
    src = """
        class Base:
            def run(self):
                return "base"

        class Sub(Base):
            forward = run_alias_target  # placeholder, replaced below
            def run(self):
                return "sub"
    """.replace("run_alias_target", "run")
    # `forward = run` above the def binds the INHERITED run... but only
    # resolves at class creation because Base.run exists in scope? No:
    # a bare `run` in a class body only sees names already bound in
    # that body — this exact source raises NameError at runtime, which
    # is the loud variant.  The lint flags the shape statically.
    findings = alias_rebind.run(_project(("a.py", src)))
    assert [f.code for f in findings] == ["L001"]
    assert "ABOVE" in findings[0].message


def test_l001_suppression_honored_and_reasonless_is_l000():
    suppressed = PRE_FIX_ALIAS.replace(
        'def run(self, q, kv):\n            return "base+sink-epilogue"',
        'def run(self, q, kv):  # graft-lint: ok forward overridden in '
        'every leaf\n            return "base+sink-epilogue"')
    assert suppressed != PRE_FIX_ALIAS
    findings = analysis.analyze_project(
        _project(("attention.py", suppressed)), bank={})
    assert [f.code for f in findings] == [], findings
    reasonless = suppressed.replace(
        "# graft-lint: ok forward overridden in every leaf",
        "# graft-lint: ok")
    findings = analysis.analyze_project(
        _project(("attention.py", reasonless)), bank={})
    assert [f.code for f in findings] == ["L000"], findings


def test_l001_real_attention_py_is_clean_post_fix():
    """The shipped fix: BatchAttention / POD / the sink wrapper all
    rebind `forward = run`; the pass agrees across the real files."""
    project = Project.from_paths([
        os.path.join(PKG_ROOT, "prefill.py"),
        os.path.join(PKG_ROOT, "attention.py"),
        os.path.join(PKG_ROOT, "sparse.py"),
        os.path.join(PKG_ROOT, "decode.py"),
        os.path.join(PKG_ROOT, "mla.py"),
    ])
    assert alias_rebind.run(project) == []


def test_forward_dispatches_to_subclass_run():
    """Runtime regression for the satellite fix itself: forward() on
    every attention.py wrapper dispatches to the SUBCLASS run and
    honors its return contract (ADVICE.md item 1)."""
    import flashinfer_tpu as fi

    assert fi.BatchAttention.forward \
        is fi.BatchAttention.run
    assert fi.PODWithPagedKVCacheWrapper.forward \
        is fi.PODWithPagedKVCacheWrapper.run
    assert fi.BatchAttentionWithAttentionSinkWrapper.forward \
        is fi.BatchAttentionWithAttentionSinkWrapper.run
    # and none of them inherited the base paged wrapper's bound alias
    base = fi.BatchPrefillWithPagedKVCacheWrapper
    for cls in (fi.BatchAttention, fi.PODWithPagedKVCacheWrapper,
                fi.BatchAttentionWithAttentionSinkWrapper):
        assert cls.forward is not base.run


# ---------------------------------------------------------------- L002 --

# the ADVICE.md round-5 item-2 shape: window_left inserted positionally
# between logits_soft_cap and q_data_type
PRE_FIX_PLAN = """
    class BatchAttention:
        def plan(self, qo_indptr, kv_indptr, kv_indices, kv_len_arr,
                 num_qo_heads, num_kv_heads, head_dim_qk, head_dim_vo,
                 page_size, causal=False, sm_scale=None,
                 logits_soft_cap=None, window_left=-1,
                 q_data_type=None, kv_data_type=None,
                 use_profiler=False):
            pass

        def run(self, q, paged_kv_cache, out=None, lse=None,
                k_scale=None, v_scale=None, logits_soft_cap=0.0,
                profiler_buffer=None, **kw):
            pass
"""

POST_FIX_PLAN = PRE_FIX_PLAN.replace(
    "logits_soft_cap=None, window_left=-1,",
    "logits_soft_cap=None, *, window_left=-1,")


def test_l002_flags_pre_fix_window_left_insertion():
    findings = signature_parity.run(
        _project(("flashinfer_tpu/attention.py", PRE_FIX_PLAN)))
    assert len(findings) == 1, findings
    f = findings[0]
    assert f.code == "L002"
    assert "window_left" in f.message and "q_data_type" in f.message


def test_l002_keyword_only_fix_is_clean():
    assert POST_FIX_PLAN != PRE_FIX_PLAN
    findings = signature_parity.run(
        _project(("flashinfer_tpu/attention.py", POST_FIX_PLAN)))
    assert findings == [], findings


def test_l002_extra_trailing_positional_flagged():
    src = POST_FIX_PLAN.replace("use_profiler=False):",
                                "use_profiler=False, extra_knob=None):")
    # keyword-only extras are fine ...
    assert signature_parity.run(_project(("flashinfer_tpu/attention.py", src))) == []
    src = PRE_FIX_PLAN.replace(
        "logits_soft_cap=None, window_left=-1,\n"
        "                 q_data_type=None, kv_data_type=None,\n"
        "                 use_profiler=False):",
        "logits_soft_cap=None, q_data_type=None, kv_data_type=None,\n"
        "                 use_profiler=False, extra_knob=None):")
    findings = signature_parity.run(_project(("flashinfer_tpu/attention.py", src)))
    # ... positional ones beyond the reference arity are not
    assert [f.code for f in findings] == ["L002"], findings
    assert "extra_knob" in findings[0].message


def test_l002_vararg_voids_loud_overflow_and_is_flagged():
    """`*args` after a matching prefix swallows a reference caller's
    extra positionals with no error — worse than either a misbind
    (caught above) or a TypeError (the accepted fix); must flag."""
    src = POST_FIX_PLAN.replace(
        "def run(self, q, paged_kv_cache, out=None, lse=None,",
        "def run(self, q, paged_kv_cache, *args, out=None, lse=None,")
    assert "*args" in src
    findings = signature_parity.run(
        _project(("flashinfer_tpu/attention.py", src)))
    assert [f.code for f in findings] == ["L002"], findings
    assert "*args" in findings[0].message


def test_l002_stale_bank_symbol_is_reported():
    """Renaming a banked method must surface, not silently drop its
    parity protection: the file matches but the qualname is gone."""
    src = POST_FIX_PLAN.replace("def run(", "def execute(")
    assert "def execute(" in src
    findings = signature_parity.run(
        _project(("flashinfer_tpu/attention.py", src)))
    assert len(findings) == 1, findings
    assert findings[0].code == "L002"
    assert "not found" in findings[0].message
    assert "BatchAttention.run" in findings[0].func


def test_l002_real_tree_matches_bank():
    """Every recorded symbol in the shipped signature bank matches the
    shipped implementation — the window_left/kv_cache_sf fixes hold."""
    project = Project.from_paths([PKG_ROOT])
    assert signature_parity.run(project) == []


def test_l002_bank_symbols_exist_in_tree():
    """A renamed/deleted method must not silently drop out of parity
    checking: every bank key resolves at its EXACT project-relative
    path in the real tree (a same-basename file elsewhere — e.g.
    parallel/attention.py — must not satisfy the check)."""
    from flashinfer_tpu.analysis.core import project_relpath

    bank = signature_parity.load_bank()
    project = Project.from_paths([PKG_ROOT])
    by_path = {}
    for sf in project.files:
        by_path[project_relpath(sf.path)] = \
            signature_parity._qualname_defs(sf)
    for key in bank:
        path, _, qualname = key.partition(":")
        assert qualname in by_path.get(path, {}), \
            f"bank symbol {key} not found — update the bank or the code"


def test_batch_attention_plan_rejects_positional_window_left():
    """Runtime regression for the satellite fix: the verbatim reference
    positional call shape (dtypes after logits_soft_cap) now fails
    LOUDLY instead of binding a dtype into window_left."""
    import jax.numpy as jnp
    import numpy as np

    import flashinfer_tpu as fi

    w = fi.BatchAttention()
    qo = np.array([0, 1], np.int32)
    kvp = np.array([0, 1], np.int32)
    kvi = np.array([0], np.int32)
    kvl = np.array([1], np.int32)
    with pytest.raises(TypeError):
        # 13th positional is the reference's q_data_type slot — the
        # pre-fix signature bound it into window_left silently
        w.plan(qo, kvp, kvi, kvl, 1, 1, 128, 128, 1, False, None, None,
               jnp.bfloat16)
    # keyword form still works and window_left stays an int
    w.plan(qo, kvp, kvi, kvl, 1, 1, 128, 128, 1, causal=False,
           q_data_type=jnp.bfloat16, window_left=-1)


def test_batch_attention_failed_replan_keeps_soft_cap_in_sync(monkeypatch):
    """A re-plan that fails INSIDE the base planner must not desync the
    logits_soft_cap run() validates against from the still-active
    previous plan (else a run passing the live plan's cap raises and a
    run passing the dead plan's cap is accepted silently)."""
    import jax.numpy as jnp
    import numpy as np

    import flashinfer_tpu as fi

    w = fi.BatchAttention()
    qo = np.array([0, 1], np.int32)
    kvp = np.array([0, 1], np.int32)
    kvi = np.array([0], np.int32)
    kvl = np.array([1], np.int32)
    w.plan(qo, kvp, kvi, kvl, 1, 1, 128, 128, 1, causal=False,
           logits_soft_cap=30.0, q_data_type=jnp.bfloat16)

    def boom(self, *a, **kw):
        raise RuntimeError("planner failure mid-replan")

    monkeypatch.setattr(
        fi.BatchPrefillWithPagedKVCacheWrapper, "plan", boom)
    with pytest.raises(RuntimeError):
        w.plan(qo, kvp, kvi, kvl, 1, 1, 128, 128, 1, causal=False,
               logits_soft_cap=50.0, q_data_type=jnp.bfloat16)
    assert w._plan_soft_cap == 30.0  # still the live plan's cap


# ---------------------------------------------------------------- L003 --

# the ADVICE.md round-5 item-4 shape: a jitted helper with `backend`
# static reaches an env read through the resolver chain
PRE_FIX_TOPK = """
    import functools
    import os

    import jax

    def _resolve_backend(backend):
        if backend == "auto":
            backend = os.environ.get("TOPK_BACKEND", "xla")
        return backend

    def top_k_values_indices(scores, k, backend="auto"):
        if _resolve_backend(backend) == "threshold":
            return "threshold", None
        return "xla", None

    @functools.partial(jax.jit, static_argnames=("k", "backend"))
    def _top_k_large_ties(scores, k, backend):
        return top_k_values_indices(scores, k, backend)
"""


def test_l003_flags_pre_fix_backend_pinning():
    findings = jit_staticness.run(_project(("compat.py", PRE_FIX_TOPK)))
    assert len(findings) == 1, findings
    f = findings[0]
    assert f.code == "L003" and f.func == "_top_k_large_ties"
    assert "top_k_values_indices" in f.message


def test_l003_direct_env_read_in_jitted_function():
    src = """
        import os
        import jax

        @jax.jit
        def f(x):
            if os.environ.get("FLAG", "0") == "1":
                return x + 1
            return x

        def eager(x):
            return os.environ.get("FLAG")  # not jitted: fine
    """
    findings = jit_staticness.run(_project(("m.py", src)))
    assert [f.func for f in findings] == ["f"]
    assert "trace time" in findings[0].message


def test_l003_jit_wrapped_assignment_form():
    src = """
        import os
        import jax

        def g(x):
            return os.getenv("FLAG")

        g_fast = jax.jit(g)
    """
    findings = jit_staticness.run(_project(("m.py", src)))
    assert [f.func for f in findings] == ["g"]


def test_l003_mutated_global_read_flagged_constant_exempt():
    src = """
        import jax

        _CACHE = {}
        _TABLE = {"a": 1}  # never mutated: a constant, exempt

        def warm(k, v):
            _CACHE[k] = v

        @jax.jit
        def f(x):
            return _CACHE.get("cfg", 0) + _TABLE["a"] + x
    """
    findings = jit_staticness.run(_project(("m.py", src)))
    assert len(findings) == 1, findings
    assert "_CACHE" in findings[0].message


def test_l003_mutated_global_taint_propagates_through_calls():
    """A mutated-global read one call deep must taint the jitted
    caller, same as an env read (the config-pinned-in-jit-cache class
    the pass documents)."""
    src = """
        import jax

        _CACHE = {}

        def warm(k, v):
            _CACHE[k] = v

        def get_cfg():
            return _CACHE.get("cfg", 0)

        @jax.jit
        def f(x):
            return get_cfg() + x
    """
    findings = jit_staticness.run(_project(("m.py", src)))
    assert [f.func for f in findings] == ["f"], findings
    assert "get_cfg" in findings[0].message


def test_l003_composed_jit_wrap_marks_inner_callable():
    """The repo's dominant launch shape — jax.jit(shard_map(step, ...))
    — must mark `step` as jitted; the step closures of every sharded
    model are exactly this population."""
    src = """
        import os
        import jax

        def make(mesh, specs):
            def step(params, x):
                if os.environ.get("FLAG"):
                    return x
                return x + 1
            return jax.jit(jax_shard_map(step, mesh=mesh, **specs))
    """
    findings = jit_staticness.run(_project(("m.py", src)))
    assert [f.func for f in findings] == ["step"], findings


def test_l003_data_args_of_composed_jit_wrap_not_marked():
    """Only the traced callable chain (first positional arg at each
    level) is jit-marked — a config/callback operand sharing a module
    function's name must not be reported as jit-traced."""
    src = """
        import os
        import jax
        import functools

        def post_fn(x):  # env-reading module function...
            return os.getenv("FLAG")

        def step(params, x):
            return x

        def make(wrap, cfg):
            # ...passed as DATA here, never traced
            return jax.jit(wrap(step, post_fn))
    """
    findings = jit_staticness.run(_project(("m.py", src)))
    assert findings == [], findings


def test_project_relpath_rightmost_marker_wins():
    """A checkout directory named flashinfer_tpu must not hijack the
    key of a tests/ file nested inside it."""
    from flashinfer_tpu.analysis.core import project_relpath

    assert project_relpath(
        "/home/u/flashinfer_tpu/tests/test_x.py") == "tests/test_x.py"
    assert project_relpath(
        "/home/u/flashinfer_tpu/flashinfer_tpu/ops/k.py"
    ) == "flashinfer_tpu/ops/k.py"


def test_l003_external_library_namesakes_not_tainted():
    """jax.lax.top_k must not inherit taint from a project function
    that happens to be called top_k (the basename-collision FP)."""
    src = """
        import os
        import jax

        def top_k(scores, k):  # project top_k: reads env
            os.environ.get("BACKEND")

        @jax.jit
        def router(logits, k):
            return jax.lax.top_k(logits, k)  # external: clean
    """
    findings = jit_staticness.run(_project(("m.py", src)))
    assert findings == [], findings


def test_l003_eager_resolution_plus_suppression_is_clean():
    """The shipped fix shape: top_k resolves the backend eagerly and the
    jitted helper carries a reasoned suppression for the now-dead
    transitive edge."""
    fixed = PRE_FIX_TOPK.replace(
        "        return top_k_values_indices(scores, k, backend)",
        "        # graft-lint: ok backend pre-resolved eagerly, never auto\n"
        "        return top_k_values_indices(scores, k, backend)")
    assert fixed != PRE_FIX_TOPK
    findings = analysis.analyze_project(
        _project(("compat.py", fixed)), bank={})
    assert findings == [], findings


def test_compat_top_k_resolves_backend_eagerly(monkeypatch):
    """Runtime regression for the satellite fix: with tie_break=LARGE,
    FLASHINFER_TPU_TOPK_BACKEND is honored per-call — the first call's
    resolution must NOT be pinned by the jit cache (ADVICE.md item 4)."""
    import jax.numpy as jnp
    import numpy as np

    import flashinfer_tpu as fi
    from flashinfer_tpu.compat import TopKTieBreak

    # On this input the backends produce a DIFFERENT output order for
    # the same top-3 set, so a pinned backend is observable: xla is
    # value-ordered; threshold emits strict entries in index order of
    # the column-reversed input ([2,4,1,5] -> 4 before 5).
    scores = jnp.asarray(np.array([[5.0, 1.0, 4.0, 2.0]], np.float32))
    monkeypatch.delenv("FLASHINFER_TPU_TOPK_BACKEND", raising=False)
    v1, i1 = fi.top_k(scores, 3, tie_break=TopKTieBreak.LARGE,
                      backend="auto")
    # flip the env var AFTER the first (cached) call — with the bug the
    # first call's in-trace "auto"->xla resolution is replayed from the
    # jit cache and the override is silently ignored
    monkeypatch.setenv("FLASHINFER_TPU_TOPK_BACKEND", "threshold")
    v2, i2 = fi.top_k(scores, 3, tie_break=TopKTieBreak.LARGE,
                      backend="auto")
    assert sorted(np.asarray(i1).ravel().tolist()) \
        == sorted(np.asarray(i2).ravel().tolist()) == [0, 2, 3]
    assert np.asarray(i1).ravel().tolist() == [0, 2, 3]  # xla: by value
    assert np.asarray(v1).ravel().tolist() == [5.0, 4.0, 2.0]
    assert np.asarray(i2).ravel().tolist() == [2, 0, 3]  # threshold
    assert np.asarray(v2).ravel().tolist() == [4.0, 5.0, 2.0]


# ---------------------------------------------------------------- L005 --


def test_l005_flags_uncataloged_decorated_op():
    src = """
        from flashinfer_tpu.api_logging import flashinfer_api

        @flashinfer_api
        def brand_new_op(x):
            return x
    """
    findings = obs_coverage.run(_project(("newmod.py", src)))
    assert [f.code for f in findings] == ["L005"], findings
    assert "brand_new_op" in findings[0].message
    assert "API_OPS" in findings[0].message


def test_l005_cataloged_ops_clean_including_name_kwarg():
    src = """
        from flashinfer_tpu.api_logging import flashinfer_api

        @flashinfer_api
        def rmsnorm(x):
            return x

        @flashinfer_api(name="silu_and_mul")
        def _impl(x):
            return x
    """
    assert obs_coverage.run(_project(("m.py", src))) == []


def test_l005_dynamic_name_is_unverifiable_and_flagged():
    src = """
        from flashinfer_tpu.api_logging import flashinfer_api

        NAME = "rmsnorm"

        @flashinfer_api(name=NAME)
        def op(x):
            return x
    """
    findings = obs_coverage.run(_project(("m.py", src)))
    assert [f.code for f in findings] == ["L005"], findings
    assert "literal" in findings[0].message


def test_l005_suppression_honored_through_driver():
    src = """
        from flashinfer_tpu.api_logging import flashinfer_api

        # graft-lint: ok internal helper, deliberately uncataloged
        def shim():
            @flashinfer_api
            def inner_op(x):
                return x
            return inner_op
    """
    findings = analysis.analyze_project(_project(("m.py", src)), bank={})
    # the suppression sits above the nested def's decorator... it must
    # be on the def line or directly above it, so this one does NOT
    # waive (two lines up) — move it adjacent and it does
    assert [f.code for f in findings] == ["L005"]
    adjacent = src.replace(
        "            @flashinfer_api\n            def inner_op(x):",
        "            @flashinfer_api\n            # graft-lint: ok "
        "internal helper, deliberately uncataloged\n"
        "            def inner_op(x):")
    findings = analysis.analyze_project(
        _project(("m.py", adjacent)), bank={})
    assert findings == [], findings


def test_l005_catalog_matches_the_decorated_tree_exactly():
    """Both directions: every decorated op is cataloged (the CI gate)
    AND every catalog entry corresponds to a real decorated function —
    a stale API_OPS entry would silently shrink the observed surface."""
    import re

    from flashinfer_tpu.obs.catalog import API_OPS

    project = Project.from_paths([PKG_ROOT])
    findings = obs_coverage.run(project, ops=frozenset())
    found = {m.group(1) for f in findings
             for m in [re.search(r"public op '([^']+)'", f.message)] if m}
    assert found == set(API_OPS)
    # and against the real catalog the tree is clean
    assert obs_coverage.run(project) == []


# ------------------------------------------------------------- driver --


def test_wedge_pass_runs_behind_driver():
    src = """
        import jax.numpy as jnp

        def lane_repeat_kernel(x_ref, o_ref):
            o_ref[...] = jnp.repeat(x_ref[...], 4, axis=-1)
    """
    findings = analysis.analyze_project(_project(("k.py", src)), bank={})
    assert [f.code for f in findings] == ["W003"]


def test_graft_suppression_applies_to_wedge_codes_via_driver():
    src = """
        import jax.numpy as jnp

        def lane_repeat_kernel(x_ref, o_ref):
            # graft-lint: ok expander-dot verified on-chip 2026-07-29
            o_ref[...] = jnp.repeat(x_ref[...], 4, axis=-1)
    """
    findings = analysis.analyze_project(_project(("k.py", src)), bank={})
    assert findings == [], findings


def test_unparseable_source_is_l999_not_a_crash():
    findings = analysis.analyze_project(
        _project(("bad.py", "def broken(:\n")), bank={})
    assert [f.code for f in findings] == ["L999"]


@pytest.mark.quick
def test_whole_tree_findings_subset_of_committed_baseline():
    """THE tier-1 CI gate: the shipped tree has no findings beyond the
    committed, triaged baseline — and the baseline carries no stale
    entries silently freeing budget for new bugs of the same shape."""
    findings = analysis.analyze_paths([PKG_ROOT])
    baseline = analysis.load_baseline()
    new, old, stale = analysis.partition_against_baseline(
        findings, baseline)
    assert new == [], "NEW findings not in baseline (fix or triage " \
        "into baseline.json):\n" + "\n".join(str(f) for f in new)
    assert stale == [], f"stale baseline entries to prune: {stale}"


def test_cli_clean_against_baseline_and_fails_without():
    assert analysis.main([PKG_ROOT]) == 0
    # the baseline is non-empty today, so --no-baseline must fail
    if analysis.load_baseline():
        assert analysis.main([PKG_ROOT, "--no-baseline"]) == 1


def test_cli_dump_signatures_smoke(capsys):
    assert analysis.main([PKG_ROOT, "--dump-signatures"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert "flashinfer_tpu/attention.py:BatchAttention.plan" in out
    ref = out["flashinfer_tpu/attention.py:BatchAttention.plan"]
    assert "window_left" in ref["implementation_kwonly"]


def test_baseline_roundtrip(tmp_path):
    findings = analysis.analyze_paths([PKG_ROOT])
    path = str(tmp_path / "baseline.json")
    analysis.write_baseline(findings, path)
    new, old, stale = analysis.partition_against_baseline(
        findings, analysis.load_baseline(path))
    assert new == [] and stale == [] and len(old) == len(findings)


def test_runtime_guard_honors_graft_suppressions():
    """A CI-blessed `# graft-lint: ok <reason>` must also satisfy the
    RUNTIME compile guard (check_module goes through lint_source): a
    suppression that passes CI but hard-blocks hardware compiles in
    strict mode would make the two gates diverge."""
    from flashinfer_tpu.analysis import wedge

    src = textwrap.dedent("""
        import jax.numpy as jnp

        def lane_repeat_kernel(x_ref, o_ref):
            # graft-lint: ok selector-matmul verified on-chip 2026-07-29
            o_ref[...] = jnp.repeat(x_ref[...], 4, axis=-1)
    """)
    assert wedge.lint_source(src, "k.py") == []
    # and reasonless graft form is a W000, exactly like the wedge form
    bare = src.replace(
        "# graft-lint: ok selector-matmul verified on-chip 2026-07-29",
        "# graft-lint: ok")
    assert [f.code for f in wedge.lint_source(bare, "k.py")] == ["W000"]


def test_orphan_reasonless_wedge_suppression_is_w000():
    """A bare '# wedge-lint: ok' that shields NOTHING is still an
    unreviewable waiver (it would silently mute the next W-finding on
    its line) — the driver must report it even though the wedge pass
    only emits W000 for shielding suppressions."""
    src = """
        def plain_helper(x):
            return x + 1  # wedge-lint: ok
    """
    findings = analysis.analyze_project(_project(("m.py", src)), bank={})
    assert [f.code for f in findings] == ["W000"], findings
    # a REASONED orphan is fine (same contract as the graft spelling)
    reasoned = src.replace("# wedge-lint: ok",
                           "# wedge-lint: ok documented-safe pattern")
    findings = analysis.analyze_project(
        _project(("m.py", reasoned)), bank={})
    assert findings == [], findings
    # and no double-report when the bare suppression DOES shield a
    # W-code (the wedge pass's own W000 wins)
    shielding = """
        import jax.numpy as jnp

        def lane_repeat_kernel(x_ref, o_ref):
            o_ref[...] = jnp.repeat(x_ref[...], 4, axis=-1)  # wedge-lint: ok
    """
    findings = analysis.analyze_project(
        _project(("k.py", shielding)), bank={})
    assert [f.code for f in findings] == ["W000"], findings


def test_write_baseline_refuses_reasonless_suppression_findings(
        tmp_path, capsys):
    """--write-baseline must never accept L000/W000: a reasonless
    waiver is definitionally un-triageable and has to be FIXED, not
    baselined into permanent silence."""
    findings = [
        analysis.Finding("L000", "flashinfer_tpu/x.py", 3,
                         "<suppression>", "no reason"),
        analysis.Finding("L003", "flashinfer_tpu/x.py", 9, "f", "env"),
    ]
    path = str(tmp_path / "b.json")
    analysis.write_baseline(findings, path)
    assert "refusing to baseline" in capsys.readouterr().out
    loaded = analysis.load_baseline(path)
    assert ("L003", "flashinfer_tpu/x.py", "f") in loaded
    assert all(code not in ("L000", "W000") for code, _, _ in loaded)
    # a hand-edited L000 entry is ignored on load as well
    data = json.load(open(path))
    data["findings"].append({"code": "L000", "path": "flashinfer_tpu/x.py",
                             "func": "<suppression>", "count": 1})
    json.dump(data, open(path, "w"))
    assert all(code != "L000" for code, _, _ in analysis.load_baseline(path))


# ------------------------------------------------------ L006 tuning_schema --


def _staged_config(tmp_path, payload):
    """A synthetic project dir: one analyzed module + a tuning_configs
    JSON next to it (the pass discovers configs project-relative)."""
    pkg = tmp_path / "pkg"
    (pkg / "tuning_configs").mkdir(parents=True)
    (pkg / "mod.py").write_text("x = 1\n")
    cfg = pkg / "tuning_configs" / "gen.json"
    cfg.write_text(payload if isinstance(payload, str)
                   else json.dumps(payload))
    return Project.from_paths([str(pkg)]), str(cfg)


def test_l006_valid_flat_and_section_entries_pass(tmp_path):
    project, _ = _staged_config(tmp_path, {
        "tactics": {"rmsnorm.row_block|1024_4096_bfloat16": 256},
        "prefill": {
            "seed": True,
            "tactics": {
                "fused_prefill.blocks|8_4096_32_8_128_16": [256, 16],
                "mla_decode.layout|a_b": "split",
            },
        },
    })
    assert tuning_schema.run(project) == []


def test_l006_stale_and_malformed_entries_flagged(tmp_path):
    project, cfg = _staged_config(tmp_path, {
        "tactics": {
            "renamed_op.blocks|8_4096": [128, 8],       # unknown knob
            "fused_prefill.blocks|8_4096": [128],       # wrong arity
            "mla_decode.layout|a": "interleaved",       # not in choices
            "rmsnorm.row_block": 128,                   # no shape part
        },
    })
    findings = tuning_schema.run(project)
    assert [f.code for f in findings] == ["L006"] * 4
    assert all(f.filename == cfg for f in findings)
    by_func = {f.func: f.message for f in findings}
    assert "unknown autotuner knob" in by_func["renamed_op.blocks|8_4096"]
    assert "2 positive ints" in by_func["fused_prefill.blocks|8_4096"]
    assert "choices" in by_func["mla_decode.layout|a"]
    assert "no shape part" in by_func["rmsnorm.row_block"]
    # findings anchor to the key's own line in the JSON
    src = open(cfg).read()
    for f in findings:
        assert json.dumps(f.func) in src.splitlines()[f.line - 1]


def test_l006_unparseable_config_is_a_finding_not_a_crash(tmp_path):
    project, cfg = _staged_config(tmp_path, "{not json")
    findings = tuning_schema.run(project)
    assert [f.code for f in findings] == ["L006"]
    assert "unreadable" in findings[0].message


def test_l006_shipped_configs_clean_and_consumed():
    """The committed tuning_configs files pass the schema gate AND the
    prefill sections actually reach the autotuner's merged table."""
    project = Project.from_paths([PKG_ROOT])
    assert tuning_schema.run(project) == []
    from flashinfer_tpu.autotuner import _flatten_config

    for stem in ("v5e", "v5p"):
        path = os.path.join(PKG_ROOT, "tuning_configs", f"{stem}.json")
        data = json.load(open(path))
        assert data["prefill"]["tactics"], stem  # populated section
        flat = _flatten_config(data)
        for key in data["prefill"]["tactics"]:
            assert key in flat, (stem, key)
        # seed labeling stays explicit until on-chip rows are banked
        assert data["prefill"]["seed"] is True


def test_flatten_config_drops_invalid_entries_and_merges_sections():
    from flashinfer_tpu.autotuner import _flatten_config

    flat = _flatten_config({
        "tactics": {
            "rmsnorm.row_block|k": 128,
            "rmsnorm.row_block|bad": "not-an-int",
            "gone_op.tiles|k": [1, 2],
        },
        "prefill": {"tactics": {"fused_prefill.blocks|k": [128, 8],
                                "rmsnorm.row_block|k": 256}},
    })
    # section entry wins on collision; invalid/unknown entries dropped
    assert flat == {"rmsnorm.row_block|k": 256,
                    "fused_prefill.blocks|k": [128, 8]}


# ---------------------------------------- L007 pallas_contract --


OPS_PREFILL = os.path.join(PKG_ROOT, "ops", "paged_prefill.py")


def _prefill_project(src):
    """The real paged_prefill.py (optionally surgically edited) as a
    one-file project — the acceptance regression runs the pass against
    the REAL planner/kernel/launch, not a toy."""
    return _project(("ops/paged_prefill.py", src))


@pytest.mark.quick
def test_l007_flags_injected_num_scalar_prefetch_skew():
    """THE acceptance regression: deliberately skewing the
    num_scalar_prefetch literal at the fused-prefill launch must fail
    L007 (both the kernel-param check and the plan-operand check)."""
    real = open(OPS_PREFILL).read()
    skew = real.replace("num_scalar_prefetch=11,",
                        "num_scalar_prefetch=10,")
    assert skew != real
    from flashinfer_tpu.analysis import pallas_contract

    findings = pallas_contract.run(_prefill_project(skew))
    assert len(findings) == 2, findings
    assert all(f.code == "L007" for f in findings)
    assert any("names 11 scalar-prefetch ref(s)" in f.message
               for f in findings)
    assert any("passes 11 plan array(s)" in f.message for f in findings)


def test_l007_flags_dropped_plan_array_operand():
    """Dropping one plan array from the launch invocation must fail.
    The operand prefix is shared by BOTH work-unit launchers (the
    attention launch and the ISSUE 14 ingest launch), so the mutation
    breaks both and each must flag independently."""
    real = open(OPS_PREFILL).read()
    drop = real.replace(
        'plan["qslot"], plan["code"], plan["pages"],',
        'plan["code"], plan["pages"],')
    assert drop != real
    from flashinfer_tpu.analysis import pallas_contract

    findings = pallas_contract.run(_prefill_project(drop))
    assert [f.code for f in findings] == ["L007", "L007"], findings
    assert {f.func for f in findings} == {
        "fused_paged_prefill", "fused_paged_prefill_ingest"}, findings
    assert all("plan array(s)" in f.message for f in findings)


def test_l007_flags_plan_key_the_planner_never_emits():
    """Dropping 'qslot' from the planner's returned dict while the
    launch still consumes plan["qslot"] must fail — the cross-function
    (planner -> launch) half of the contract."""
    real = open(OPS_PREFILL).read()
    dropkey = real.replace(
        "qslot=np.asarray(qslot, np.int32), code=arr(6, np.int32),",
        "code=arr(6, np.int32),")
    assert dropkey != real
    from flashinfer_tpu.analysis import pallas_contract

    findings = pallas_contract.run(_prefill_project(dropkey))
    assert [f.code for f in findings] == ["L007"], findings
    assert "qslot" in findings[0].message
    assert "build_prefill_work_units" in findings[0].message


def test_l007_index_map_arity_and_kernel_arity_fixture():
    src = """
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        def _k(x_ref, o_ref, acc_ref, extra_ref):
            o_ref[...] = x_ref[...]

        def launch(x):
            return pl.pallas_call(
                _k,
                grid=(4, 2),
                in_specs=[pl.BlockSpec((8, 128), lambda i: (0, 0))],
                out_specs=pl.BlockSpec((8, 128), lambda i, j: (0, 0)),
                scratch_shapes=[pltpu.VMEM((8, 128), jnp.float32)],
            )(x)
    """
    from flashinfer_tpu.analysis import pallas_contract

    findings = pallas_contract.run(_project(("k.py", src)))
    # one index_map arity finding (lambda i vs rank-2 grid) and one
    # kernel arity finding (4 params vs 1+1+1=3)
    assert len(findings) == 2, findings
    assert all(f.code == "L007" for f in findings)
    assert any("index_map takes 1 parameter(s)" in f.message
               for f in findings)
    assert any("takes 4 positional ref(s)" in f.message
               for f in findings)


def test_l007_positional_partial_binds_counted_out():
    """partial(_k, True) consumes the kernel's leading param: the
    3-param kernel launched with 2 specs must NOT be flagged."""
    src = """
        import functools
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def _k(causal, x_ref, o_ref):
            o_ref[...] = x_ref[...]

        def launch(x):
            return pl.pallas_call(
                functools.partial(_k, True),
                grid=(4,),
                in_specs=[pl.BlockSpec((8, 128), lambda i: (0, 0))],
                out_specs=pl.BlockSpec((8, 128), lambda i: (0, 0)),
            )(x)
    """
    from flashinfer_tpu.analysis import pallas_contract

    assert pallas_contract.run(_project(("k.py", src))) == []


def test_l007_unresolvable_registered_planner_skips():
    """A subset run missing the registered planner's module must skip
    the planner checks, not report — --changed-only analyzes partial
    trees and can only under-report, never false-fail."""
    real = open(OPS_PREFILL).read()
    # strip the planner def so only the launch half is in the project
    launch_only = real.replace("def build_prefill_work_units",
                               "def _renamed_away_planner")
    assert launch_only != real
    from flashinfer_tpu.analysis import pallas_contract

    findings = pallas_contract.run(_prefill_project(launch_only))
    assert findings == [], findings


def test_l007_shadowing_param_does_not_resolve_to_outer_assign():
    """An inner function's parameter must be UNRESOLVABLE, not fall
    through to a shadowed outer once-assigned name — the launch takes
    whatever list the caller passes at runtime."""
    src = """
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def _k(x_ref, o_ref):
            o_ref[...] = x_ref[...]

        def outer(x):
            specs = [pl.BlockSpec((8, 128), lambda i: (0, 0)),
                     pl.BlockSpec((8, 128), lambda i: (0, 0))]

            def inner(specs):
                return pl.pallas_call(
                    _k,
                    grid=(4,),
                    in_specs=specs,
                    out_specs=pl.BlockSpec((8, 128), lambda i: (0, 0)),
                )(x)

            return inner([pl.BlockSpec((8, 128), lambda i: (0, 0))])
    """
    from flashinfer_tpu.analysis import pallas_contract

    assert pallas_contract.run(_project(("k.py", src))) == []


def test_l007_cross_module_planner_resolution():
    """Planner in one module, launch in another: the registry check
    resolves through the project symbol index."""
    planner = """
        import numpy as np

        def build_prefill_work_units(n):
            return dict(qstart=np.zeros(n), kvlen=np.zeros(n))
    """
    launch = """
        import functools
        import jax.numpy as jnp
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        def _fused_prefill_kernel(qstart_ref, kvlen_ref, *refs, bq):
            pass

        def go(plan, q):
            grid_spec = pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(4,),
                in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
                out_specs=pl.BlockSpec(memory_space=pl.ANY),
                scratch_shapes=[],
            )
            return pl.pallas_call(
                functools.partial(_fused_prefill_kernel, bq=8),
                grid_spec=grid_spec,
                out_shape=q,
            )(plan["qstart"], plan["MISSING"], q)
    """
    from flashinfer_tpu.analysis import pallas_contract

    findings = pallas_contract.run(
        _project(("planner.py", planner), ("launchmod.py", launch)))
    assert [f.code for f in findings] == ["L007"], findings
    assert "MISSING" in findings[0].message


def test_l007_to_l010_real_tree_clean():
    """Clean-tree pin for ALL four kernel-contract passes on one shared
    Project (pallas_sites resolve once): the shipped planner/kernel/
    launch triples agree, no traced-value leaks, shipped configs fit
    VMEM, accumulators are initialized — with NO baseline absorption
    (the passes themselves return nothing)."""
    from flashinfer_tpu.analysis import (kernel_init_guard,
                                         pallas_contract, tracer_leak,
                                         vmem_budget)

    project = Project.from_paths([PKG_ROOT])
    assert pallas_contract.run(project) == []
    assert tracer_leak.run(project) == []
    assert vmem_budget.run(project) == []
    assert kernel_init_guard.run(project) == []


# ------------------------------------------- L008 tracer_leak --


@pytest.mark.quick
def test_l008_flags_traced_control_flow_and_concretization():
    src = """
        import jax
        import numpy as np

        @jax.jit
        def f(x, n):
            if x > 0:
                x = x + 1
            k = int(n)
            y = np.sum(x)
            z = x.item()
            assert x > 0
            return x
    """
    from flashinfer_tpu.analysis import tracer_leak

    findings = tracer_leak.run(_project(("m.py", src)))
    assert len(findings) == 5, findings
    msgs = " | ".join(f.message for f in findings)
    assert "Python if" in msgs
    assert "int()" in msgs
    assert "np.sum()" in msgs
    assert ".item()" in msgs
    assert "assert" in msgs


def test_l008_static_args_shape_and_structure_are_exempt():
    src = """
        import functools
        import jax

        @functools.partial(jax.jit, static_argnames=("n",))
        def g(x, n):
            if n > 2:
                return x
            total, d = x.shape
            if total > 8:
                return x
            has = x is not None
            if has:
                return x
            while d > 1:
                d //= 2
            return x
    """
    from flashinfer_tpu.analysis import tracer_leak

    assert tracer_leak.run(_project(("m.py", src))) == []


def test_l008_pallas_kernel_refs_are_traced_kwonly_static():
    src = """
        import jax.numpy as jnp
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        def _k(x_ref, o_ref, *, causal):
            if causal:          # partial-bound static: fine
                pass
            if x_ref[0] > 0:    # traced ref read: leak
                pass
            o_ref[...] = x_ref[...]

        def launch(x):
            import functools
            return pl.pallas_call(
                functools.partial(_k, causal=True),
                grid=(4,),
                in_specs=[pl.BlockSpec((8, 128), lambda i: (0, 0))],
                out_specs=pl.BlockSpec((8, 128), lambda i: (0, 0)),
            )(x)
    """
    from flashinfer_tpu.analysis import tracer_leak

    findings = tracer_leak.run(_project(("k.py", src)))
    assert [f.code for f in findings] == ["L008"], findings
    assert findings[0].func == "_k"


def test_l008_positionally_bound_kernel_static_exempt():
    """partial(_k, True): the leading positional param is a launch
    static, not a traced ref — branching on it must not be flagged."""
    src = """
        import functools
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def _k(causal, x_ref, o_ref):
            if causal:
                o_ref[...] = x_ref[...]

        def launch(x):
            return pl.pallas_call(
                functools.partial(_k, True),
                grid=(4,),
                in_specs=[pl.BlockSpec((8, 128), lambda i: (0, 0))],
                out_specs=pl.BlockSpec((8, 128), lambda i: (0, 0)),
            )(x)
    """
    from flashinfer_tpu.analysis import tracer_leak

    assert tracer_leak.run(_project(("k.py", src))) == []


# ------------------------------------------- L009 vmem_budget --


def _staged_vmem_project(tmp_path, blocks):
    """A synthetic project holding the REAL fused-prefill launcher and
    one tuning config naming the given blocks for a huge page_size."""
    pkg = tmp_path / "pkg"
    (pkg / "tuning_configs").mkdir(parents=True)
    (pkg / "mod.py").write_text(open(OPS_PREFILL).read())
    cfg = pkg / "tuning_configs" / "v5e.json"
    cfg.write_text(json.dumps({
        "tactics": {
            "fused_prefill.blocks|8_4096_32_8_128_16384": blocks,
        },
    }))
    return Project.from_paths([str(pkg)]), str(cfg)


@pytest.mark.quick
def test_l009_flags_blocks_that_cannot_fit_vmem(tmp_path):
    from flashinfer_tpu.analysis import vmem_budget

    project, cfg = _staged_vmem_project(tmp_path, [8192, 512])
    findings = vmem_budget.run(project)
    assert [f.code for f in findings] == ["L009"], findings
    f = findings[0]
    assert f.filename == cfg
    assert "vmem_limit_bytes=64 MiB" in f.message
    assert "can never compile" in f.message
    # findings anchor to the key's line in the JSON
    assert json.dumps(f.func) in open(cfg).read().splitlines()[f.line - 1]


def test_l009_sane_blocks_pass(tmp_path):
    from flashinfer_tpu.analysis import vmem_budget

    project, _ = _staged_vmem_project(tmp_path, [128, 1])
    assert vmem_budget.run(project) == []


def test_l009_conditional_assignments_min_merge():
    """A write under an If may not execute: the evaluator must keep the
    SMALLEST value on any path, or 'cannot fit' stops being a proof."""
    import ast as ast_mod

    from flashinfer_tpu.analysis.vmem_budget import _Evaluator

    fn = ast_mod.parse(textwrap.dedent("""
        def launcher(total_q, block_q):
            bq = 64
            if total_q > 512:
                bq = block_q
            else:
                bq = 32
    """)).body[0]
    ev = _Evaluator({"total_q": 256, "block_q": 8192}, 2)
    ev.run_body(fn)
    assert ev.env["bq"] == 32  # NOT 8192 (last-write-wins would)


def test_l007_absent_scratch_shapes_counts_as_zero():
    """Omitting scratch_shapes= is statically ZERO scratch refs — the
    kernel-arity check must still run and catch the extra param."""
    src = """
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def _k(x_ref, o_ref, ghost_ref):
            o_ref[...] = x_ref[...]

        def launch(x):
            return pl.pallas_call(
                _k,
                grid=(4,),
                in_specs=[pl.BlockSpec((8, 128), lambda i: (0, 0))],
                out_specs=pl.BlockSpec((8, 128), lambda i: (0, 0)),
            )(x)
    """
    from flashinfer_tpu.analysis import pallas_contract

    findings = pallas_contract.run(_project(("k.py", src)))
    assert any(f.code == "L007" and "3 positional ref(s)" in f.message
               for f in findings), findings


def test_l009_estimate_is_physically_plausible():
    """The symbolic evaluator reproduces the hand-computed scratch
    footprint of the fused-prefill kernel for a known shape."""
    from flashinfer_tpu.analysis.vmem_budget import KNOB_LAUNCHES, _estimate

    project = Project.from_paths([PKG_ROOT])
    est = _estimate(project, KNOB_LAUNCHES["fused_prefill.blocks"],
                    [256, 16], "8_4096_32_8_128_16".split("_"))
    assert est is not None
    total, budget, _launcher = est
    # bq=256 group=4 D=128 chunk=256: qbuf 512K + k/v 256K + obuf 256K
    # + acc 512K + m/l 1M  ≈ 2.6 MB
    assert 2_000_000 < total < 3_500_000, total
    assert budget == 64 * 1024 * 1024


# -------------------------------------- L010 kernel_init_guard --


L010_KERNEL = """
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def _acc_kernel(x_ref, o_ref, acc_ref):
        i = pl.program_id(0)

        @pl.when(i != 0)
        def _():
            acc_ref[...] = acc_ref[...] + x_ref[...]

        o_ref[...] = acc_ref[...]

    def launch(x):
        return pl.pallas_call(
            _acc_kernel,
            grid=(4,),
            in_specs=[pl.BlockSpec((8, 128), lambda i: (0, 0))],
            out_specs=pl.BlockSpec((8, 128), lambda i: (0, 0)),
            scratch_shapes=[pltpu.VMEM((8, 128), jnp.float32)],
        )(x)
"""


@pytest.mark.quick
def test_l010_flags_uninitialized_guarded_accumulator():
    from flashinfer_tpu.analysis import kernel_init_guard

    findings = kernel_init_guard.run(_project(("k.py", L010_KERNEL)))
    assert [f.code for f in findings] == ["L010"], findings
    assert "acc_ref" in findings[0].message
    assert "EXCLUDE the first grid step" in findings[0].message


def test_l010_step_zero_init_write_is_clean():
    fixed = L010_KERNEL.replace(
        "        o_ref[...] = acc_ref[...]",
        """\
        @pl.when(i == 0)
        def _():
            acc_ref[...] = jnp.zeros_like(acc_ref)

        o_ref[...] = acc_ref[...]""")
    assert fixed != L010_KERNEL
    from flashinfer_tpu.analysis import kernel_init_guard

    assert kernel_init_guard.run(_project(("k.py", fixed))) == []


def test_l010_value_guards_are_not_step_guards():
    """`pl.when(num_chunks > 0)` gates work, not steps — it must not
    classify as excluding (the mla_decode/paged_decode idiom)."""
    src = L010_KERNEL.replace("@pl.when(i != 0)",
                              "@pl.when(x_ref[0] > 0)")
    assert src != L010_KERNEL
    from flashinfer_tpu.analysis import kernel_init_guard

    assert kernel_init_guard.run(_project(("k.py", src))) == []


def test_l010_input_output_alias_bounds():
    src = L010_KERNEL.replace(
        "scratch_shapes=[pltpu.VMEM((8, 128), jnp.float32)],",
        "scratch_shapes=[pltpu.VMEM((8, 128), jnp.float32)],\n"
        "            input_output_aliases={3: 2},")
    # silence the accumulator finding: this test is about the aliases
    src = src.replace("@pl.when(i != 0)", "@pl.when(i == 0)")
    from flashinfer_tpu.analysis import kernel_init_guard

    findings = kernel_init_guard.run(_project(("k.py", src)))
    assert len(findings) == 2, findings
    msgs = " | ".join(f.message for f in findings)
    assert "key 3 is out of range" in msgs
    assert "value 2 is out of range" in msgs


# ------------------------------------------------- SARIF surface --


# A faithful subset of the SARIF 2.1.0 schema (oasis-tcs/sarif-spec
# Schemata/sarif-schema-2.1.0.json): the required/enum constraints for
# every node the exporter emits.  Validated with jsonschema so a
# structural regression (missing version, results without messages,
# bad level enum) fails here rather than at GitHub upload time.
SARIF_SCHEMA_SUBSET = {
    "type": "object",
    "required": ["version", "runs"],
    "properties": {
        "version": {"enum": ["2.1.0"]},
        "$schema": {"type": "string", "format": "uri"},
        "runs": {
            "type": "array",
            "items": {
                "type": "object",
                "required": ["tool"],
                "properties": {
                    "tool": {
                        "type": "object",
                        "required": ["driver"],
                        "properties": {
                            "driver": {
                                "type": "object",
                                "required": ["name"],
                                "properties": {
                                    "name": {"type": "string"},
                                    "rules": {
                                        "type": "array",
                                        "items": {
                                            "type": "object",
                                            "required": ["id"],
                                        },
                                    },
                                },
                            },
                        },
                    },
                    "results": {
                        "type": "array",
                        "items": {
                            "type": "object",
                            "required": ["message"],
                            "properties": {
                                "message": {
                                    "type": "object",
                                    "required": ["text"],
                                },
                                "level": {"enum": [
                                    "none", "note", "warning", "error"]},
                                "ruleId": {"type": "string"},
                                "locations": {
                                    "type": "array",
                                    "items": {
                                        "type": "object",
                                        "properties": {
                                            "physicalLocation": {
                                                "type": "object",
                                                "properties": {
                                                    "artifactLocation": {
                                                        "type": "object",
                                                        "properties": {
                                                            "uri": {
                                                                "type":
                                                                "string"},
                                                        },
                                                    },
                                                    "region": {
                                                        "type": "object",
                                                        "properties": {
                                                            "startLine": {
                                                                "type":
                                                                "integer",
                                                                "minimum":
                                                                1},
                                                        },
                                                    },
                                                },
                                            },
                                        },
                                    },
                                },
                            },
                        },
                    },
                },
            },
        },
    },
}


@pytest.mark.quick
def test_sarif_output_validates_against_schema():
    import jsonschema

    from flashinfer_tpu.analysis import sarif as sarif_mod

    findings = [
        analysis.Finding("L007", "flashinfer_tpu/ops/x.py", 3, "launch",
                         "skewed"),
        analysis.Finding("L000", "flashinfer_tpu/y.py", 0,
                         "<suppression>", "no reason"),
    ]
    doc = sarif_mod.to_sarif(findings)
    jsonschema.validate(doc, SARIF_SCHEMA_SUBSET)
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "graft-lint"
    assert [r["ruleId"] for r in run["results"]] == ["L007", "L000"]
    # line 0 is clamped to the schema's minimum
    assert run["results"][1]["locations"][0]["physicalLocation"][
        "region"]["startLine"] == 1
    # rules cover exactly the emitted codes
    assert {r["id"] for r in run["tool"]["driver"]["rules"]} \
        == {"L000", "L007"}
    # empty-findings document is also valid (the CI always-upload path)
    jsonschema.validate(sarif_mod.to_sarif([]), SARIF_SCHEMA_SUBSET)


def test_cli_sarif_flag_writes_new_findings(tmp_path, capsys):
    """--sarif writes the NON-baselined findings: a clean single-file
    run produces a valid empty SARIF, and a file with a real finding
    lands in the document (single-file runs keep the tier-1 cost down;
    the whole-tree CLI run is covered by
    test_cli_clean_against_baseline_and_fails_without)."""
    import jsonschema

    out = tmp_path / "out.sarif"
    clean = os.path.join(PKG_ROOT, "attention.py")
    assert analysis.main([clean, "--sarif", str(out)]) == 0
    doc = json.loads(out.read_text())
    jsonschema.validate(doc, SARIF_SCHEMA_SUBSET)
    assert doc["runs"][0]["results"] == []
    # a self-contained wedge fixture surfaces its finding in the doc
    # (the tree's own baselined L003s are transitive — a single-file
    # run cannot see their cross-module callees, so a fixture it is)
    noisy = tmp_path / "wedgy.py"
    noisy.write_text(WEDGY)
    assert analysis.main(
        [str(noisy), "--no-baseline", "--sarif", str(out)]) == 1
    doc = json.loads(out.read_text())
    jsonschema.validate(doc, SARIF_SCHEMA_SUBSET)
    results = doc["runs"][0]["results"]
    assert results and all(r["ruleId"] == "W003" for r in results)
    assert all(r["locations"][0]["physicalLocation"]["artifactLocation"]
               ["uri"] == "wedgy.py" for r in results)


# ------------------------------------------- --changed-only mode --


def _git(repo, *args):
    import subprocess

    r = subprocess.run(
        ["git", "-C", str(repo), "-c", "user.email=t@t",
         "-c", "user.name=t", *args],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    return r.stdout


WEDGY = """
import jax.numpy as jnp


def lane_repeat_kernel(x_ref, o_ref):
    o_ref[...] = jnp.repeat(x_ref[...], 4, axis=-1)
"""


@pytest.mark.quick
def test_changed_only_analyzes_only_the_changed_module(tmp_path, capsys):
    """A one-file diff analyzes only that file's modules: the unchanged
    file's finding must NOT appear, the changed file's must."""
    repo = tmp_path / "repo"
    repo.mkdir()
    _git(repo, "init", "-q")
    (repo / "clean_a.py").write_text("x = 1\n")
    (repo / "clean_b.py").write_text(WEDGY)  # committed: not "changed"
    _git(repo, "add", "-A")
    _git(repo, "commit", "-qm", "seed")
    (repo / "clean_a.py").write_text(WEDGY.replace(
        "lane_repeat_kernel", "other_repeat_kernel"))
    rc = analysis.main([str(repo), "--changed-only", "--no-baseline"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "other_repeat_kernel" in out   # the changed file's finding
    assert "lane_repeat_kernel" not in out  # unchanged file not analyzed
    assert "1 finding(s)" in out


def test_changed_only_config_json_diff_runs_full_analysis(tmp_path,
                                                          capsys):
    """A tuning_configs/*.json-only diff must NOT report 'no analyzed
    files changed' — L006/L009 lint exactly those files, so the CLI
    falls back to full analysis."""
    repo = tmp_path / "repo"
    (repo / "tuning_configs").mkdir(parents=True)
    _git(repo, "init", "-q")
    (repo / "mod.py").write_text(WEDGY)
    (repo / "tuning_configs" / "v5e.json").write_text("{}\n")
    _git(repo, "add", "-A")
    _git(repo, "commit", "-qm", "seed")
    (repo / "tuning_configs" / "v5e.json").write_text(
        '{"tactics": {}}\n')
    rc = analysis.main([str(repo), "--changed-only", "--no-baseline"])
    out = capsys.readouterr().out
    assert rc == 1  # the full run sees mod.py's wedge finding
    assert "no analyzed files changed" not in out
    assert "lane_repeat_kernel" in out


def test_changed_only_clean_diff_exits_zero(tmp_path, capsys):
    repo = tmp_path / "repo"
    repo.mkdir()
    _git(repo, "init", "-q")
    (repo / "mod.py").write_text(WEDGY)
    _git(repo, "add", "-A")
    _git(repo, "commit", "-qm", "seed")
    rc = analysis.main([str(repo), "--changed-only", "--no-baseline"])
    assert rc == 0
    assert "no analyzed files changed" in capsys.readouterr().out


def test_whole_tree_run_reports_deleted_file_stale_entries(tmp_path,
                                                           capsys):
    """A baseline entry naming a file that no longer exists must still
    print as stale on a whole-tree run — that's the deleted/renamed
    module case pruning exists for."""
    import flashinfer_tpu.analysis as analysis_mod

    real = json.load(open(analysis_mod.DEFAULT_BASELINE_PATH))
    real["findings"].append({
        "code": "L003", "path": "flashinfer_tpu/deleted_module.py",
        "func": "gone", "count": 1, "lines_at_capture": [1]})
    fake = tmp_path / "b.json"
    fake.write_text(json.dumps(real))
    rc = analysis.main([PKG_ROOT, "--baseline", str(fake)])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "deleted_module.py" in out
    assert "1 stale" in out


def test_write_baseline_refuses_subset_runs(tmp_path, capsys):
    """--write-baseline on a subset (one file / --changed-only) would
    truncate the committed baseline to what the partial tree shows —
    the CLI must refuse."""
    one = os.path.join(PKG_ROOT, "attention.py")
    out = tmp_path / "b.json"
    rc = analysis.main([one, "--write-baseline",
                        "--baseline", str(out)])
    assert rc == 2
    assert not out.exists()
    assert "whole-tree" in capsys.readouterr().err


def test_subset_run_does_not_report_foreign_stale_entries(capsys):
    """Analyzing one file against the full baseline must not claim
    every other file's baselined findings are stale."""
    one = os.path.join(PKG_ROOT, "attention.py")
    rc = analysis.main([one])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "stale baseline entry (no longer fires" not in out
    assert "0 stale" in out


# ------------------------- satellite: per-run soft-cap rebind --


def _plan_batch_attention(w, soft_cap):
    import numpy as np

    qo = np.array([0, 2, 4], np.int32)
    kvp = np.array([0, 2, 4], np.int32)
    kvi = np.arange(4, dtype=np.int32)
    kvl = np.array([8, 8], np.int32)
    w.plan(qo, kvp, kvi, kvl, 4, 2, 64, 64, 4, causal=True,
           logits_soft_cap=soft_cap)


def _soft_cap_inputs():
    import jax
    import jax.numpy as jnp

    key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, (4, 4, 64), jnp.bfloat16)
    kc = jax.random.normal(jax.random.fold_in(key, 1), (4, 4, 2, 64),
                           jnp.bfloat16)
    vc = jax.random.normal(jax.random.fold_in(key, 2), (4, 4, 2, 64),
                           jnp.bfloat16)
    return q, (kc, vc)


def test_batch_attention_run_honors_differing_soft_cap():
    """ADVICE r5 item 3 (resolved): a per-run logits_soft_cap differing
    from the planned one takes effect for that call — the verbatim
    reference call shape — instead of raising; the plan's own cap is
    restored afterwards."""
    import numpy as np

    import flashinfer_tpu as fi

    q, kv = _soft_cap_inputs()
    w = fi.BatchAttention(kv_layout="NHD")
    _plan_batch_attention(w, 30.0)
    out_30, _ = w.run(q, kv)
    out_50_rebound, _ = w.run(q, kv, logits_soft_cap=50.0)
    # plan restored after the rebound call
    assert w._plan.logits_soft_cap == 30.0
    out_30_again, _ = w.run(q, kv)
    np.testing.assert_array_equal(np.asarray(out_30),
                                  np.asarray(out_30_again))

    # ground truth: a wrapper PLANNED at 50 produces the rebound output
    w50 = fi.BatchAttention(kv_layout="NHD")
    _plan_batch_attention(w50, 50.0)
    out_50_planned, _ = w50.run(q, kv)
    np.testing.assert_array_equal(np.asarray(out_50_rebound),
                                  np.asarray(out_50_planned))
    # and the capped outputs genuinely differ from the 30-cap ones
    assert not np.array_equal(np.asarray(out_30),
                              np.asarray(out_50_rebound))


def test_batch_attention_soft_cap_rebind_counted(monkeypatch):
    import flashinfer_tpu as fi
    from flashinfer_tpu import obs

    monkeypatch.setenv("FLASHINFER_TPU_METRICS", "1")
    obs.reset()
    try:
        q, kv = _soft_cap_inputs()
        w = fi.BatchAttention(kv_layout="NHD")
        _plan_batch_attention(w, 30.0)
        w.run(q, kv, logits_soft_cap=50.0)   # differing: rebinds
        w.run(q, kv, logits_soft_cap=30.0)   # matching: no rebind
        w.run(q, kv)                         # default: inherits, none
        snap = obs.snapshot()
        assert snap["counters"]["plan.soft_cap_rebinds"][
            "{wrapper=BatchAttention}"] == 1
    finally:
        obs.reset()


# ----------------------------- satellite: wedge_lint shim retired --


def test_wedge_lint_shim_is_retired():
    """The PR 4 DeprecationWarning shim is gone (ISSUE 15): the wedge
    lint is importable ONLY from analysis.wedge, and compile_guard's
    runtime hook already goes there directly."""
    import ast as _ast
    import importlib
    import inspect as _inspect

    import pytest as _pytest

    with _pytest.raises(ModuleNotFoundError):
        importlib.import_module("flashinfer_tpu.wedge_lint")
    from flashinfer_tpu import compile_guard

    src = _inspect.getsource(compile_guard)
    assert "from flashinfer_tpu.analysis import wedge" in src
    for node in _ast.walk(_ast.parse(src)):
        if isinstance(node, _ast.ImportFrom):
            assert not any(a.name == "wedge_lint" for a in node.names), \
                "compile_guard must not import the retired shim"


# ---------------------------------- driver: all seventeen passes --


def test_driver_runs_all_seventeen_passes():
    """Registration pin for the grown driver: L001–L017 all behind the
    one driver (a pass that exists but is not in PASSES silently never
    runs — exactly the silent-skip failure mode L013 exists to kill)."""
    from flashinfer_tpu.analysis import (chooser_coverage, cost_parity,
                                         dma_race, donation_lifetime,
                                         kernel_init_guard,
                                         mosaic_lowering, pallas_contract,
                                         registry_coverage, static_flow,
                                         tracer_leak, vmem_budget)

    for p in (pallas_contract, tracer_leak, vmem_budget,
              kernel_init_guard, donation_lifetime, static_flow,
              registry_coverage, dma_race, mosaic_lowering,
              cost_parity, chooser_coverage):
        assert p in analysis.PASSES, p.__name__
    assert len(analysis.PASSES) == 17
