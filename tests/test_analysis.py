"""Multi-pass static analyzer (flashinfer_tpu.analysis).

Each pass must flag the EXACT pre-fix ADVICE.md round-5 bug shape it
was built from (true positive), honor reasoned ``# graft-lint: ok``
suppressions (rejecting reasonless ones as L000), and stay quiet on the
fixed/clean shape.  The whole-tree run over ``flashinfer_tpu/`` against
the committed baseline is the tier-1 CI gate: new findings fail the
suite at review time, not at the next advisor round.
"""

import json
import os
import textwrap

import pytest

from flashinfer_tpu import analysis
from flashinfer_tpu.analysis import (alias_rebind, jit_staticness,
                                     obs_coverage, signature_parity,
                                     tuning_schema)
from flashinfer_tpu.analysis.core import Project, load_source

PKG_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "flashinfer_tpu"))


def _project(*named_sources):
    return Project([load_source(textwrap.dedent(src), name)
                    for name, src in named_sources])


# ---------------------------------------------------------------- L001 --

# the ADVICE.md round-5 item-1 shape: the paged base wrapper binds
# `forward = run` at class-definition time; subclasses redefine run
PRE_FIX_ALIAS = """
    class BasePagedWrapper:
        def run(self, q, kv):
            return "base"
        forward = run

    class SinkWrapper(BasePagedWrapper):
        def run(self, q, kv):
            return "base+sink-epilogue"
"""

POST_FIX_ALIAS = PRE_FIX_ALIAS + """\
        forward = run
"""


def test_l001_flags_pre_fix_sink_wrapper_shape():
    findings = alias_rebind.run(_project(("attention.py", PRE_FIX_ALIAS)))
    assert len(findings) == 1, findings
    f = findings[0]
    assert f.code == "L001" and f.func == "SinkWrapper.run"
    assert "forward = run" in f.message and "SinkWrapper" in f.message
    # the runtime truth the lint models: the inherited alias really does
    # call the BASE run
    ns = {}
    exec(textwrap.dedent(PRE_FIX_ALIAS), ns)
    assert ns["SinkWrapper"]().forward(0, 0) == "base"  # the silent bug


def test_l001_rebind_fix_is_clean():
    findings = alias_rebind.run(_project(("attention.py", POST_FIX_ALIAS)))
    assert findings == [], findings
    ns = {}
    exec(textwrap.dedent(POST_FIX_ALIAS), ns)
    assert ns["SinkWrapper"]().forward(0, 0) == "base+sink-epilogue"


def test_l001_resolves_bases_across_files():
    """The real bug spanned prefill.py (alias) and attention.py
    (subclass) — the pass must resolve inheritance project-wide."""
    base = """
        class BasePagedWrapper:
            def run(self, q, kv):
                return "base"
            forward = run
    """
    sub = """
        class BatchAttention(BasePagedWrapper):
            def run(self, q, kv):
                return "holistic"
    """
    findings = alias_rebind.run(
        _project(("prefill.py", base), ("attention.py", sub)))
    assert [f.code for f in findings] == ["L001"]
    assert findings[0].filename == "attention.py"


def test_l001_grandchild_inheriting_redefined_run_flagged():
    """'inheriting a redefined run': the grandchild's forward skips the
    override it actually inherits, even though it defines nothing."""
    src = PRE_FIX_ALIAS + """
    class DerivedOfSink(SinkWrapper):
        pass
    """
    findings = alias_rebind.run(_project(("a.py", src)))
    assert {f.func for f in findings} == {"SinkWrapper.run",
                                          "DerivedOfSink"}


def test_l001_alias_above_def_in_same_class_flagged():
    src = """
        class Base:
            def run(self):
                return "base"

        class Sub(Base):
            forward = run_alias_target  # placeholder, replaced below
            def run(self):
                return "sub"
    """.replace("run_alias_target", "run")
    # `forward = run` above the def binds the INHERITED run... but only
    # resolves at class creation because Base.run exists in scope? No:
    # a bare `run` in a class body only sees names already bound in
    # that body — this exact source raises NameError at runtime, which
    # is the loud variant.  The lint flags the shape statically.
    findings = alias_rebind.run(_project(("a.py", src)))
    assert [f.code for f in findings] == ["L001"]
    assert "ABOVE" in findings[0].message


def test_l001_suppression_honored_and_reasonless_is_l000():
    suppressed = PRE_FIX_ALIAS.replace(
        'def run(self, q, kv):\n            return "base+sink-epilogue"',
        'def run(self, q, kv):  # graft-lint: ok forward overridden in '
        'every leaf\n            return "base+sink-epilogue"')
    assert suppressed != PRE_FIX_ALIAS
    findings = analysis.analyze_project(
        _project(("attention.py", suppressed)), bank={})
    assert [f.code for f in findings] == [], findings
    reasonless = suppressed.replace(
        "# graft-lint: ok forward overridden in every leaf",
        "# graft-lint: ok")
    findings = analysis.analyze_project(
        _project(("attention.py", reasonless)), bank={})
    assert [f.code for f in findings] == ["L000"], findings


def test_l001_real_attention_py_is_clean_post_fix():
    """The shipped fix: BatchAttention / POD / the sink wrapper all
    rebind `forward = run`; the pass agrees across the real files."""
    project = Project.from_paths([
        os.path.join(PKG_ROOT, "prefill.py"),
        os.path.join(PKG_ROOT, "attention.py"),
        os.path.join(PKG_ROOT, "sparse.py"),
        os.path.join(PKG_ROOT, "decode.py"),
        os.path.join(PKG_ROOT, "mla.py"),
    ])
    assert alias_rebind.run(project) == []


def test_forward_dispatches_to_subclass_run():
    """Runtime regression for the satellite fix itself: forward() on
    every attention.py wrapper dispatches to the SUBCLASS run and
    honors its return contract (ADVICE.md item 1)."""
    import flashinfer_tpu as fi

    assert fi.BatchAttention.forward \
        is fi.BatchAttention.run
    assert fi.PODWithPagedKVCacheWrapper.forward \
        is fi.PODWithPagedKVCacheWrapper.run
    assert fi.BatchAttentionWithAttentionSinkWrapper.forward \
        is fi.BatchAttentionWithAttentionSinkWrapper.run
    # and none of them inherited the base paged wrapper's bound alias
    base = fi.BatchPrefillWithPagedKVCacheWrapper
    for cls in (fi.BatchAttention, fi.PODWithPagedKVCacheWrapper,
                fi.BatchAttentionWithAttentionSinkWrapper):
        assert cls.forward is not base.run


# ---------------------------------------------------------------- L002 --

# the ADVICE.md round-5 item-2 shape: window_left inserted positionally
# between logits_soft_cap and q_data_type
PRE_FIX_PLAN = """
    class BatchAttention:
        def plan(self, qo_indptr, kv_indptr, kv_indices, kv_len_arr,
                 num_qo_heads, num_kv_heads, head_dim_qk, head_dim_vo,
                 page_size, causal=False, sm_scale=None,
                 logits_soft_cap=None, window_left=-1,
                 q_data_type=None, kv_data_type=None,
                 use_profiler=False):
            pass

        def run(self, q, paged_kv_cache, out=None, lse=None,
                k_scale=None, v_scale=None, logits_soft_cap=0.0,
                profiler_buffer=None, **kw):
            pass
"""

POST_FIX_PLAN = PRE_FIX_PLAN.replace(
    "logits_soft_cap=None, window_left=-1,",
    "logits_soft_cap=None, *, window_left=-1,")


def test_l002_flags_pre_fix_window_left_insertion():
    findings = signature_parity.run(
        _project(("flashinfer_tpu/attention.py", PRE_FIX_PLAN)))
    assert len(findings) == 1, findings
    f = findings[0]
    assert f.code == "L002"
    assert "window_left" in f.message and "q_data_type" in f.message


def test_l002_keyword_only_fix_is_clean():
    assert POST_FIX_PLAN != PRE_FIX_PLAN
    findings = signature_parity.run(
        _project(("flashinfer_tpu/attention.py", POST_FIX_PLAN)))
    assert findings == [], findings


def test_l002_extra_trailing_positional_flagged():
    src = POST_FIX_PLAN.replace("use_profiler=False):",
                                "use_profiler=False, extra_knob=None):")
    # keyword-only extras are fine ...
    assert signature_parity.run(_project(("flashinfer_tpu/attention.py", src))) == []
    src = PRE_FIX_PLAN.replace(
        "logits_soft_cap=None, window_left=-1,\n"
        "                 q_data_type=None, kv_data_type=None,\n"
        "                 use_profiler=False):",
        "logits_soft_cap=None, q_data_type=None, kv_data_type=None,\n"
        "                 use_profiler=False, extra_knob=None):")
    findings = signature_parity.run(_project(("flashinfer_tpu/attention.py", src)))
    # ... positional ones beyond the reference arity are not
    assert [f.code for f in findings] == ["L002"], findings
    assert "extra_knob" in findings[0].message


def test_l002_vararg_voids_loud_overflow_and_is_flagged():
    """`*args` after a matching prefix swallows a reference caller's
    extra positionals with no error — worse than either a misbind
    (caught above) or a TypeError (the accepted fix); must flag."""
    src = POST_FIX_PLAN.replace(
        "def run(self, q, paged_kv_cache, out=None, lse=None,",
        "def run(self, q, paged_kv_cache, *args, out=None, lse=None,")
    assert "*args" in src
    findings = signature_parity.run(
        _project(("flashinfer_tpu/attention.py", src)))
    assert [f.code for f in findings] == ["L002"], findings
    assert "*args" in findings[0].message


def test_l002_stale_bank_symbol_is_reported():
    """Renaming a banked method must surface, not silently drop its
    parity protection: the file matches but the qualname is gone."""
    src = POST_FIX_PLAN.replace("def run(", "def execute(")
    assert "def execute(" in src
    findings = signature_parity.run(
        _project(("flashinfer_tpu/attention.py", src)))
    assert len(findings) == 1, findings
    assert findings[0].code == "L002"
    assert "not found" in findings[0].message
    assert "BatchAttention.run" in findings[0].func


def test_l002_real_tree_matches_bank():
    """Every recorded symbol in the shipped signature bank matches the
    shipped implementation — the window_left/kv_cache_sf fixes hold."""
    project = Project.from_paths([PKG_ROOT])
    assert signature_parity.run(project) == []


def test_l002_bank_symbols_exist_in_tree():
    """A renamed/deleted method must not silently drop out of parity
    checking: every bank key resolves at its EXACT project-relative
    path in the real tree (a same-basename file elsewhere — e.g.
    parallel/attention.py — must not satisfy the check)."""
    from flashinfer_tpu.analysis.core import project_relpath

    bank = signature_parity.load_bank()
    project = Project.from_paths([PKG_ROOT])
    by_path = {}
    for sf in project.files:
        by_path[project_relpath(sf.path)] = \
            signature_parity._qualname_defs(sf)
    for key in bank:
        path, _, qualname = key.partition(":")
        assert qualname in by_path.get(path, {}), \
            f"bank symbol {key} not found — update the bank or the code"


def test_batch_attention_plan_rejects_positional_window_left():
    """Runtime regression for the satellite fix: the verbatim reference
    positional call shape (dtypes after logits_soft_cap) now fails
    LOUDLY instead of binding a dtype into window_left."""
    import jax.numpy as jnp
    import numpy as np

    import flashinfer_tpu as fi

    w = fi.BatchAttention()
    qo = np.array([0, 1], np.int32)
    kvp = np.array([0, 1], np.int32)
    kvi = np.array([0], np.int32)
    kvl = np.array([1], np.int32)
    with pytest.raises(TypeError):
        # 13th positional is the reference's q_data_type slot — the
        # pre-fix signature bound it into window_left silently
        w.plan(qo, kvp, kvi, kvl, 1, 1, 128, 128, 1, False, None, None,
               jnp.bfloat16)
    # keyword form still works and window_left stays an int
    w.plan(qo, kvp, kvi, kvl, 1, 1, 128, 128, 1, causal=False,
           q_data_type=jnp.bfloat16, window_left=-1)


def test_batch_attention_failed_replan_keeps_soft_cap_in_sync(monkeypatch):
    """A re-plan that fails INSIDE the base planner must not desync the
    logits_soft_cap run() validates against from the still-active
    previous plan (else a run passing the live plan's cap raises and a
    run passing the dead plan's cap is accepted silently)."""
    import jax.numpy as jnp
    import numpy as np

    import flashinfer_tpu as fi

    w = fi.BatchAttention()
    qo = np.array([0, 1], np.int32)
    kvp = np.array([0, 1], np.int32)
    kvi = np.array([0], np.int32)
    kvl = np.array([1], np.int32)
    w.plan(qo, kvp, kvi, kvl, 1, 1, 128, 128, 1, causal=False,
           logits_soft_cap=30.0, q_data_type=jnp.bfloat16)

    def boom(self, *a, **kw):
        raise RuntimeError("planner failure mid-replan")

    monkeypatch.setattr(
        fi.BatchPrefillWithPagedKVCacheWrapper, "plan", boom)
    with pytest.raises(RuntimeError):
        w.plan(qo, kvp, kvi, kvl, 1, 1, 128, 128, 1, causal=False,
               logits_soft_cap=50.0, q_data_type=jnp.bfloat16)
    assert w._plan_soft_cap == 30.0  # still the live plan's cap


# ---------------------------------------------------------------- L003 --

# the ADVICE.md round-5 item-4 shape: a jitted helper with `backend`
# static reaches an env read through the resolver chain
PRE_FIX_TOPK = """
    import functools
    import os

    import jax

    def _resolve_backend(backend):
        if backend == "auto":
            backend = os.environ.get("TOPK_BACKEND", "xla")
        return backend

    def top_k_values_indices(scores, k, backend="auto"):
        if _resolve_backend(backend) == "threshold":
            return "threshold", None
        return "xla", None

    @functools.partial(jax.jit, static_argnames=("k", "backend"))
    def _top_k_large_ties(scores, k, backend):
        return top_k_values_indices(scores, k, backend)
"""


def test_l003_flags_pre_fix_backend_pinning():
    findings = jit_staticness.run(_project(("compat.py", PRE_FIX_TOPK)))
    assert len(findings) == 1, findings
    f = findings[0]
    assert f.code == "L003" and f.func == "_top_k_large_ties"
    assert "top_k_values_indices" in f.message


def test_l003_direct_env_read_in_jitted_function():
    src = """
        import os
        import jax

        @jax.jit
        def f(x):
            if os.environ.get("FLAG", "0") == "1":
                return x + 1
            return x

        def eager(x):
            return os.environ.get("FLAG")  # not jitted: fine
    """
    findings = jit_staticness.run(_project(("m.py", src)))
    assert [f.func for f in findings] == ["f"]
    assert "trace time" in findings[0].message


def test_l003_jit_wrapped_assignment_form():
    src = """
        import os
        import jax

        def g(x):
            return os.getenv("FLAG")

        g_fast = jax.jit(g)
    """
    findings = jit_staticness.run(_project(("m.py", src)))
    assert [f.func for f in findings] == ["g"]


def test_l003_mutated_global_read_flagged_constant_exempt():
    src = """
        import jax

        _CACHE = {}
        _TABLE = {"a": 1}  # never mutated: a constant, exempt

        def warm(k, v):
            _CACHE[k] = v

        @jax.jit
        def f(x):
            return _CACHE.get("cfg", 0) + _TABLE["a"] + x
    """
    findings = jit_staticness.run(_project(("m.py", src)))
    assert len(findings) == 1, findings
    assert "_CACHE" in findings[0].message


def test_l003_mutated_global_taint_propagates_through_calls():
    """A mutated-global read one call deep must taint the jitted
    caller, same as an env read (the config-pinned-in-jit-cache class
    the pass documents)."""
    src = """
        import jax

        _CACHE = {}

        def warm(k, v):
            _CACHE[k] = v

        def get_cfg():
            return _CACHE.get("cfg", 0)

        @jax.jit
        def f(x):
            return get_cfg() + x
    """
    findings = jit_staticness.run(_project(("m.py", src)))
    assert [f.func for f in findings] == ["f"], findings
    assert "get_cfg" in findings[0].message


def test_l003_composed_jit_wrap_marks_inner_callable():
    """The repo's dominant launch shape — jax.jit(shard_map(step, ...))
    — must mark `step` as jitted; the step closures of every sharded
    model are exactly this population."""
    src = """
        import os
        import jax

        def make(mesh, specs):
            def step(params, x):
                if os.environ.get("FLAG"):
                    return x
                return x + 1
            return jax.jit(jax_shard_map(step, mesh=mesh, **specs))
    """
    findings = jit_staticness.run(_project(("m.py", src)))
    assert [f.func for f in findings] == ["step"], findings


def test_l003_data_args_of_composed_jit_wrap_not_marked():
    """Only the traced callable chain (first positional arg at each
    level) is jit-marked — a config/callback operand sharing a module
    function's name must not be reported as jit-traced."""
    src = """
        import os
        import jax
        import functools

        def post_fn(x):  # env-reading module function...
            return os.getenv("FLAG")

        def step(params, x):
            return x

        def make(wrap, cfg):
            # ...passed as DATA here, never traced
            return jax.jit(wrap(step, post_fn))
    """
    findings = jit_staticness.run(_project(("m.py", src)))
    assert findings == [], findings


def test_project_relpath_rightmost_marker_wins():
    """A checkout directory named flashinfer_tpu must not hijack the
    key of a tests/ file nested inside it."""
    from flashinfer_tpu.analysis.core import project_relpath

    assert project_relpath(
        "/home/u/flashinfer_tpu/tests/test_x.py") == "tests/test_x.py"
    assert project_relpath(
        "/home/u/flashinfer_tpu/flashinfer_tpu/ops/k.py"
    ) == "flashinfer_tpu/ops/k.py"


def test_l003_external_library_namesakes_not_tainted():
    """jax.lax.top_k must not inherit taint from a project function
    that happens to be called top_k (the basename-collision FP)."""
    src = """
        import os
        import jax

        def top_k(scores, k):  # project top_k: reads env
            os.environ.get("BACKEND")

        @jax.jit
        def router(logits, k):
            return jax.lax.top_k(logits, k)  # external: clean
    """
    findings = jit_staticness.run(_project(("m.py", src)))
    assert findings == [], findings


def test_l003_eager_resolution_plus_suppression_is_clean():
    """The shipped fix shape: top_k resolves the backend eagerly and the
    jitted helper carries a reasoned suppression for the now-dead
    transitive edge."""
    fixed = PRE_FIX_TOPK.replace(
        "        return top_k_values_indices(scores, k, backend)",
        "        # graft-lint: ok backend pre-resolved eagerly, never auto\n"
        "        return top_k_values_indices(scores, k, backend)")
    assert fixed != PRE_FIX_TOPK
    findings = analysis.analyze_project(
        _project(("compat.py", fixed)), bank={})
    assert findings == [], findings


def test_compat_top_k_resolves_backend_eagerly(monkeypatch):
    """Runtime regression for the satellite fix: with tie_break=LARGE,
    FLASHINFER_TPU_TOPK_BACKEND is honored per-call — the first call's
    resolution must NOT be pinned by the jit cache (ADVICE.md item 4)."""
    import jax.numpy as jnp
    import numpy as np

    import flashinfer_tpu as fi
    from flashinfer_tpu.compat import TopKTieBreak

    # On this input the backends produce a DIFFERENT output order for
    # the same top-3 set, so a pinned backend is observable: xla is
    # value-ordered; threshold emits strict entries in index order of
    # the column-reversed input ([2,4,1,5] -> 4 before 5).
    scores = jnp.asarray(np.array([[5.0, 1.0, 4.0, 2.0]], np.float32))
    monkeypatch.delenv("FLASHINFER_TPU_TOPK_BACKEND", raising=False)
    v1, i1 = fi.top_k(scores, 3, tie_break=TopKTieBreak.LARGE,
                      backend="auto")
    # flip the env var AFTER the first (cached) call — with the bug the
    # first call's in-trace "auto"->xla resolution is replayed from the
    # jit cache and the override is silently ignored
    monkeypatch.setenv("FLASHINFER_TPU_TOPK_BACKEND", "threshold")
    v2, i2 = fi.top_k(scores, 3, tie_break=TopKTieBreak.LARGE,
                      backend="auto")
    assert sorted(np.asarray(i1).ravel().tolist()) \
        == sorted(np.asarray(i2).ravel().tolist()) == [0, 2, 3]
    assert np.asarray(i1).ravel().tolist() == [0, 2, 3]  # xla: by value
    assert np.asarray(v1).ravel().tolist() == [5.0, 4.0, 2.0]
    assert np.asarray(i2).ravel().tolist() == [2, 0, 3]  # threshold
    assert np.asarray(v2).ravel().tolist() == [4.0, 5.0, 2.0]


# ---------------------------------------------------------------- L005 --


def test_l005_flags_uncataloged_decorated_op():
    src = """
        from flashinfer_tpu.api_logging import flashinfer_api

        @flashinfer_api
        def brand_new_op(x):
            return x
    """
    findings = obs_coverage.run(_project(("newmod.py", src)))
    assert [f.code for f in findings] == ["L005"], findings
    assert "brand_new_op" in findings[0].message
    assert "API_OPS" in findings[0].message


def test_l005_cataloged_ops_clean_including_name_kwarg():
    src = """
        from flashinfer_tpu.api_logging import flashinfer_api

        @flashinfer_api
        def rmsnorm(x):
            return x

        @flashinfer_api(name="silu_and_mul")
        def _impl(x):
            return x
    """
    assert obs_coverage.run(_project(("m.py", src))) == []


def test_l005_dynamic_name_is_unverifiable_and_flagged():
    src = """
        from flashinfer_tpu.api_logging import flashinfer_api

        NAME = "rmsnorm"

        @flashinfer_api(name=NAME)
        def op(x):
            return x
    """
    findings = obs_coverage.run(_project(("m.py", src)))
    assert [f.code for f in findings] == ["L005"], findings
    assert "literal" in findings[0].message


def test_l005_suppression_honored_through_driver():
    src = """
        from flashinfer_tpu.api_logging import flashinfer_api

        # graft-lint: ok internal helper, deliberately uncataloged
        def shim():
            @flashinfer_api
            def inner_op(x):
                return x
            return inner_op
    """
    findings = analysis.analyze_project(_project(("m.py", src)), bank={})
    # the suppression sits above the nested def's decorator... it must
    # be on the def line or directly above it, so this one does NOT
    # waive (two lines up) — move it adjacent and it does
    assert [f.code for f in findings] == ["L005"]
    adjacent = src.replace(
        "            @flashinfer_api\n            def inner_op(x):",
        "            @flashinfer_api\n            # graft-lint: ok "
        "internal helper, deliberately uncataloged\n"
        "            def inner_op(x):")
    findings = analysis.analyze_project(
        _project(("m.py", adjacent)), bank={})
    assert findings == [], findings


def test_l005_catalog_matches_the_decorated_tree_exactly():
    """Both directions: every decorated op is cataloged (the CI gate)
    AND every catalog entry corresponds to a real decorated function —
    a stale API_OPS entry would silently shrink the observed surface."""
    import re

    from flashinfer_tpu.obs.catalog import API_OPS

    project = Project.from_paths([PKG_ROOT])
    findings = obs_coverage.run(project, ops=frozenset())
    found = {m.group(1) for f in findings
             for m in [re.search(r"public op '([^']+)'", f.message)] if m}
    assert found == set(API_OPS)
    # and against the real catalog the tree is clean
    assert obs_coverage.run(project) == []


# ------------------------------------------------------------- driver --


def test_wedge_pass_runs_behind_driver():
    src = """
        import jax.numpy as jnp

        def lane_repeat_kernel(x_ref, o_ref):
            o_ref[...] = jnp.repeat(x_ref[...], 4, axis=-1)
    """
    findings = analysis.analyze_project(_project(("k.py", src)), bank={})
    assert [f.code for f in findings] == ["W003"]


def test_graft_suppression_applies_to_wedge_codes_via_driver():
    src = """
        import jax.numpy as jnp

        def lane_repeat_kernel(x_ref, o_ref):
            # graft-lint: ok expander-dot verified on-chip 2026-07-29
            o_ref[...] = jnp.repeat(x_ref[...], 4, axis=-1)
    """
    findings = analysis.analyze_project(_project(("k.py", src)), bank={})
    assert findings == [], findings


def test_unparseable_source_is_l999_not_a_crash():
    findings = analysis.analyze_project(
        _project(("bad.py", "def broken(:\n")), bank={})
    assert [f.code for f in findings] == ["L999"]


@pytest.mark.quick
def test_whole_tree_findings_subset_of_committed_baseline():
    """THE tier-1 CI gate: the shipped tree has no findings beyond the
    committed, triaged baseline — and the baseline carries no stale
    entries silently freeing budget for new bugs of the same shape."""
    findings = analysis.analyze_paths([PKG_ROOT])
    baseline = analysis.load_baseline()
    new, old, stale = analysis.partition_against_baseline(
        findings, baseline)
    assert new == [], "NEW findings not in baseline (fix or triage " \
        "into baseline.json):\n" + "\n".join(str(f) for f in new)
    assert stale == [], f"stale baseline entries to prune: {stale}"


def test_cli_clean_against_baseline_and_fails_without():
    assert analysis.main([PKG_ROOT]) == 0
    # the baseline is non-empty today, so --no-baseline must fail
    if analysis.load_baseline():
        assert analysis.main([PKG_ROOT, "--no-baseline"]) == 1


def test_cli_dump_signatures_smoke(capsys):
    assert analysis.main([PKG_ROOT, "--dump-signatures"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert "flashinfer_tpu/attention.py:BatchAttention.plan" in out
    ref = out["flashinfer_tpu/attention.py:BatchAttention.plan"]
    assert "window_left" in ref["implementation_kwonly"]


def test_baseline_roundtrip(tmp_path):
    findings = analysis.analyze_paths([PKG_ROOT])
    path = str(tmp_path / "baseline.json")
    analysis.write_baseline(findings, path)
    new, old, stale = analysis.partition_against_baseline(
        findings, analysis.load_baseline(path))
    assert new == [] and stale == [] and len(old) == len(findings)


def test_runtime_guard_honors_graft_suppressions():
    """A CI-blessed `# graft-lint: ok <reason>` must also satisfy the
    RUNTIME compile guard (check_module goes through lint_source): a
    suppression that passes CI but hard-blocks hardware compiles in
    strict mode would make the two gates diverge."""
    from flashinfer_tpu.analysis import wedge

    src = textwrap.dedent("""
        import jax.numpy as jnp

        def lane_repeat_kernel(x_ref, o_ref):
            # graft-lint: ok selector-matmul verified on-chip 2026-07-29
            o_ref[...] = jnp.repeat(x_ref[...], 4, axis=-1)
    """)
    assert wedge.lint_source(src, "k.py") == []
    # and reasonless graft form is a W000, exactly like the wedge form
    bare = src.replace(
        "# graft-lint: ok selector-matmul verified on-chip 2026-07-29",
        "# graft-lint: ok")
    assert [f.code for f in wedge.lint_source(bare, "k.py")] == ["W000"]


def test_orphan_reasonless_wedge_suppression_is_w000():
    """A bare '# wedge-lint: ok' that shields NOTHING is still an
    unreviewable waiver (it would silently mute the next W-finding on
    its line) — the driver must report it even though the wedge pass
    only emits W000 for shielding suppressions."""
    src = """
        def plain_helper(x):
            return x + 1  # wedge-lint: ok
    """
    findings = analysis.analyze_project(_project(("m.py", src)), bank={})
    assert [f.code for f in findings] == ["W000"], findings
    # a REASONED orphan is fine (same contract as the graft spelling)
    reasoned = src.replace("# wedge-lint: ok",
                           "# wedge-lint: ok documented-safe pattern")
    findings = analysis.analyze_project(
        _project(("m.py", reasoned)), bank={})
    assert findings == [], findings
    # and no double-report when the bare suppression DOES shield a
    # W-code (the wedge pass's own W000 wins)
    shielding = """
        import jax.numpy as jnp

        def lane_repeat_kernel(x_ref, o_ref):
            o_ref[...] = jnp.repeat(x_ref[...], 4, axis=-1)  # wedge-lint: ok
    """
    findings = analysis.analyze_project(
        _project(("k.py", shielding)), bank={})
    assert [f.code for f in findings] == ["W000"], findings


def test_write_baseline_refuses_reasonless_suppression_findings(
        tmp_path, capsys):
    """--write-baseline must never accept L000/W000: a reasonless
    waiver is definitionally un-triageable and has to be FIXED, not
    baselined into permanent silence."""
    findings = [
        analysis.Finding("L000", "flashinfer_tpu/x.py", 3,
                         "<suppression>", "no reason"),
        analysis.Finding("L003", "flashinfer_tpu/x.py", 9, "f", "env"),
    ]
    path = str(tmp_path / "b.json")
    analysis.write_baseline(findings, path)
    assert "refusing to baseline" in capsys.readouterr().out
    loaded = analysis.load_baseline(path)
    assert ("L003", "flashinfer_tpu/x.py", "f") in loaded
    assert all(code not in ("L000", "W000") for code, _, _ in loaded)
    # a hand-edited L000 entry is ignored on load as well
    data = json.load(open(path))
    data["findings"].append({"code": "L000", "path": "flashinfer_tpu/x.py",
                             "func": "<suppression>", "count": 1})
    json.dump(data, open(path, "w"))
    assert all(code != "L000" for code, _, _ in analysis.load_baseline(path))


def test_wedge_lint_shim_surface():
    """compile_guard and the historical tests import these names from
    flashinfer_tpu.wedge_lint — the shim must keep them working."""
    from flashinfer_tpu import wedge_lint as wl
    from flashinfer_tpu.analysis import wedge

    assert wl.lint_source is wedge.lint_source
    assert wl.check_module is wedge.check_module
    assert wl.WedgeLintError is wedge.WedgeLintError
    assert wl.Finding is analysis.Finding
    assert wl.DOT_UNROLL_LIMIT == wedge.DOT_UNROLL_LIMIT


# ------------------------------------------------------ L006 tuning_schema --


def _staged_config(tmp_path, payload):
    """A synthetic project dir: one analyzed module + a tuning_configs
    JSON next to it (the pass discovers configs project-relative)."""
    pkg = tmp_path / "pkg"
    (pkg / "tuning_configs").mkdir(parents=True)
    (pkg / "mod.py").write_text("x = 1\n")
    cfg = pkg / "tuning_configs" / "gen.json"
    cfg.write_text(payload if isinstance(payload, str)
                   else json.dumps(payload))
    return Project.from_paths([str(pkg)]), str(cfg)


def test_l006_valid_flat_and_section_entries_pass(tmp_path):
    project, _ = _staged_config(tmp_path, {
        "tactics": {"rmsnorm.row_block|1024_4096_bfloat16": 256},
        "prefill": {
            "seed": True,
            "tactics": {
                "fused_prefill.blocks|8_4096_32_8_128_16": [256, 16],
                "mla_decode.layout|a_b": "split",
            },
        },
    })
    assert tuning_schema.run(project) == []


def test_l006_stale_and_malformed_entries_flagged(tmp_path):
    project, cfg = _staged_config(tmp_path, {
        "tactics": {
            "renamed_op.blocks|8_4096": [128, 8],       # unknown knob
            "fused_prefill.blocks|8_4096": [128],       # wrong arity
            "mla_decode.layout|a": "interleaved",       # not in choices
            "rmsnorm.row_block": 128,                   # no shape part
        },
    })
    findings = tuning_schema.run(project)
    assert [f.code for f in findings] == ["L006"] * 4
    assert all(f.filename == cfg for f in findings)
    by_func = {f.func: f.message for f in findings}
    assert "unknown autotuner knob" in by_func["renamed_op.blocks|8_4096"]
    assert "2 positive ints" in by_func["fused_prefill.blocks|8_4096"]
    assert "choices" in by_func["mla_decode.layout|a"]
    assert "no shape part" in by_func["rmsnorm.row_block"]
    # findings anchor to the key's own line in the JSON
    src = open(cfg).read()
    for f in findings:
        assert json.dumps(f.func) in src.splitlines()[f.line - 1]


def test_l006_unparseable_config_is_a_finding_not_a_crash(tmp_path):
    project, cfg = _staged_config(tmp_path, "{not json")
    findings = tuning_schema.run(project)
    assert [f.code for f in findings] == ["L006"]
    assert "unreadable" in findings[0].message


def test_l006_shipped_configs_clean_and_consumed():
    """The committed tuning_configs files pass the schema gate AND the
    prefill sections actually reach the autotuner's merged table."""
    project = Project.from_paths([PKG_ROOT])
    assert tuning_schema.run(project) == []
    from flashinfer_tpu.autotuner import _flatten_config

    for stem in ("v5e", "v5p"):
        path = os.path.join(PKG_ROOT, "tuning_configs", f"{stem}.json")
        data = json.load(open(path))
        assert data["prefill"]["tactics"], stem  # populated section
        flat = _flatten_config(data)
        for key in data["prefill"]["tactics"]:
            assert key in flat, (stem, key)
        # seed labeling stays explicit until on-chip rows are banked
        assert data["prefill"]["seed"] is True


def test_flatten_config_drops_invalid_entries_and_merges_sections():
    from flashinfer_tpu.autotuner import _flatten_config

    flat = _flatten_config({
        "tactics": {
            "rmsnorm.row_block|k": 128,
            "rmsnorm.row_block|bad": "not-an-int",
            "gone_op.tiles|k": [1, 2],
        },
        "prefill": {"tactics": {"fused_prefill.blocks|k": [128, 8],
                                "rmsnorm.row_block|k": 256}},
    })
    # section entry wins on collision; invalid/unknown entries dropped
    assert flat == {"rmsnorm.row_block|k": 256,
                    "fused_prefill.blocks|k": [128, 8]}
