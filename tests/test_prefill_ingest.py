"""Fused prefill INGEST parity suite (ISSUE 14 tentpole proof).

The fused launch — RoPE + KV-quantize-append + attention folded into
the work-unit prefill mainloop (``ops/paged_prefill.
fused_paged_prefill_ingest``) — is pinned against the separate-op
ORACLE composition: ``rotate_at_positions_static`` -> the matching
``append_paged_kv_cache[_quant_{int8,fp8}]`` -> the proven work-unit
attention kernel.  The bar (ISSUE 14 acceptance):

- **f32 is bitwise.**  Same rotation math (constant-base freq pow),
  same online-softmax walk — output AND cache bits identical.
- **Quantized caches are bit-for-bit.**  The in-kernel quantize is the
  quant-append formula verbatim, so int8/fp8 cache bits equal the
  composed append's on every valid row (rows past a sequence's end in
  its last partial page are deterministically zeroed by the fused
  write-back — excluded by contract, see the kernel docstring).
- Causal / windowed / packed-custom-mask rungs all hold, write-only
  units (chunks attention pruned everywhere) still reach the cache,
  and the append-only form serves the ``rope_quantize_fp8_append_
  paged_kv_cache`` reroute with the composed tier as its oracle.
- The serving adoptions keep their token pins: MixedServingStep
  fused-vs-composed samples identical tokens; the engine kernel tier
  dispatches per step by VALUE so the one-trace-per-rung budget holds.
- The analysis registrations (L007 planner pair, L009 knob launch,
  L006 tuning sections) cannot skew from the real modules.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from flashinfer_tpu.ops.paged_prefill import (
    CODE_WRITE_ONLY,
    build_prefill_ingest_units,
    build_prefill_work_units,
    fused_paged_prefill,
    fused_paged_prefill_ingest,
)
from flashinfer_tpu.page import (
    append_paged_kv_cache,
    append_paged_kv_cache_quant_fp8,
    append_paged_kv_cache_quant_int8,
)
from flashinfer_tpu.rope import rotate_at_positions_static

HQ, HKV, D, PS = 4, 2, 32, 8
BQ, PPC = 32, 2

# from-scratch ingest geometries: qo_lens == kv_lens (the raw rows ARE
# the planned KV axis); mixed ragged includes a zero-length request
GEOMETRIES = {
    "uniform": [64, 64, 64],
    "ragged": [40, 7, 130, 0, 65],
    "single_long": [192],
}


def _setup(lens, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    qo_indptr = np.concatenate([[0], np.cumsum(lens)]).astype(np.int64)
    pages_per = [int(np.ceil(n / PS)) for n in lens]
    kv_page_indptr = np.concatenate([[0], np.cumsum(pages_per)]).astype(
        np.int64)
    npages = max(int(kv_page_indptr[-1]), 1)
    kv_page_indices = rng.permutation(npages).astype(np.int64)
    total = int(qo_indptr[-1])
    q = jax.random.normal(jax.random.PRNGKey(seed), (total, HQ, D), dtype)
    k = jax.random.normal(jax.random.PRNGKey(seed + 1), (total, HKV, D),
                          dtype)
    v = jax.random.normal(jax.random.PRNGKey(seed + 2), (total, HKV, D),
                          dtype)
    return qo_indptr, kv_page_indptr, kv_page_indices, q, k, v


def _positions(lens):
    kv_pos = np.concatenate(
        [np.arange(n) for n in lens] or [np.zeros(0)]).astype(np.int32)
    kv_req = np.repeat(np.arange(len(lens)), lens).astype(np.int32)
    return kv_pos, kv_req


def _fused(qo_indptr, kv_page_indptr, kv_page_indices, lens, q, k, v,
           kc, vc, *, causal=True, window_left=-1, mask_flat=None,
           mask_total_bits=None, kv_quant="none", ks=1.0, vs=1.0,
           attend=True, fused_ingest=True):
    plan_np = build_prefill_ingest_units(
        qo_indptr, kv_page_indptr, kv_page_indices,
        np.asarray(lens, np.int64), block_q=BQ, pages_per_chunk=PPC,
        page_size=PS, mask_flat=mask_flat,
        mask_total_bits=mask_total_bits, causal=causal,
        window_left=window_left, fused_ingest=fused_ingest,
    )
    statics = dict(num_units=plan_np.pop("num_units"),
                   block_q=plan_np.pop("block_q"),
                   pages_per_chunk=plan_np.pop("pages_per_chunk"))
    stats = plan_np.pop("stats")
    plan = {kk: jnp.asarray(vv) for kk, vv in plan_np.items()}
    total = int(qo_indptr[-1])
    if attend:
        tq_pad = max(BQ, -(-total // BQ) * BQ)
        qp = jnp.pad(q, ((0, tq_pad - total), (0, 0), (0, 0)))
    else:
        qp = None
    out = fused_paged_prefill_ingest(
        qp, k, v, kc, vc, plan, sm_scale=D ** -0.5, causal=causal,
        window_left=window_left, attend=attend, kv_quant=kv_quant,
        k_scale=ks, v_scale=vs, **statics,
    )
    if not attend:
        return out, stats
    o, caches = out
    return o[:total], caches, stats


def _composed(qo_indptr, kv_page_indptr, kv_page_indices, lens, q, k, v,
              kc, vc, *, causal=True, window_left=-1, mask_flat=None,
              mask_total_bits=None, kv_quant="none", ks=1.0, vs=1.0):
    """The separate-op oracle: static-rotate -> matching append ->
    work-unit attention over the post-append cache, scales folded the
    decode-kernel way (k into sm, v on the output)."""
    kv_pos, kv_req = _positions(lens)
    q_rot = rotate_at_positions_static(q, jnp.asarray(
        np.concatenate([np.arange(n) for n in lens] or [np.zeros(0)])
        .astype(np.int32)))
    k_rot = rotate_at_positions_static(k, jnp.asarray(kv_pos))
    kvi = jnp.asarray(kv_page_indices)
    kvp = jnp.asarray(kv_page_indptr)
    if kv_quant == "int8":
        caches = append_paged_kv_cache_quant_int8(
            k_rot, v, jnp.asarray(kv_req), jnp.asarray(kv_pos), (kc, vc),
            kvi, kvp, jnp.float32(ks), jnp.float32(vs), "HND")
    elif kv_quant == "fp8":
        caches = append_paged_kv_cache_quant_fp8(
            k_rot, v, jnp.asarray(kv_req), jnp.asarray(kv_pos), (kc, vc),
            kvi, kvp, jnp.float32(ks), jnp.float32(vs), "HND")
    else:
        caches = append_paged_kv_cache(
            k_rot, v, jnp.asarray(kv_req), jnp.asarray(kv_pos), (kc, vc),
            kvi, kvp, None, "HND")
    plan_np = build_prefill_work_units(
        qo_indptr, kv_page_indptr, kv_page_indices,
        np.asarray(lens, np.int64), block_q=BQ, pages_per_chunk=PPC,
        page_size=PS, mask_flat=mask_flat,
        mask_total_bits=mask_total_bits, causal=causal,
        window_left=window_left,
    )
    statics = dict(num_units=plan_np.pop("num_units"),
                   block_q=plan_np.pop("block_q"),
                   pages_per_chunk=plan_np.pop("pages_per_chunk"))
    plan_np.pop("stats")
    plan = {kk: jnp.asarray(vv) for kk, vv in plan_np.items()}
    total = int(qo_indptr[-1])
    tq_pad = max(BQ, -(-total // BQ) * BQ)
    qp = jnp.pad(q_rot, ((0, tq_pad - total), (0, 0), (0, 0)))
    sm = D ** -0.5 * (ks if kv_quant != "none" else 1.0)
    out = fused_paged_prefill(
        qp, caches[0], caches[1], plan, sm_scale=sm, causal=causal,
        window_left=window_left, **statics)[:total]
    if kv_quant != "none":
        out = (out.astype(jnp.float32) * vs).astype(q.dtype)
    return out, caches


def _valid_cache_rows(kv_page_indptr, kv_page_indices, lens, cache):
    """Flat [sum(lens), HKV, D] view of the cache's VALID rows only
    (rows past each sequence's end are outside the parity contract)."""
    rows = []
    arr = np.asarray(cache)
    for r, n in enumerate(lens):
        pages = kv_page_indices[kv_page_indptr[r]:kv_page_indptr[r + 1]]
        for j, p in enumerate(pages):
            nn = min(PS, n - j * PS)
            rows.append(arr[p].transpose(1, 0, 2)[:nn])
    return np.concatenate(rows) if rows else np.zeros((0, HKV, D))


# ---------------------------------------------------------------------------
# kernel-level fused-vs-composed parity
# ---------------------------------------------------------------------------


@pytest.mark.quick
@pytest.mark.parametrize("geom", sorted(GEOMETRIES))
def test_fused_vs_composed_f32_bitwise(geom):
    """f32: output and cache bits of the fused launch == the separate
    rotate -> append -> attend composition, bitwise."""
    lens = GEOMETRIES[geom]
    qo, kvp, kvi, q, k, v = _setup(lens, seed=1)
    npages = max(int(kvp[-1]), 1)
    z = lambda: jnp.zeros((npages, HKV, PS, D), jnp.float32)
    o_f, (kc_f, vc_f), stats = _fused(qo, kvp, kvi, lens, q, k, v,
                                      z(), z())
    o_c, (kc_c, vc_c) = _composed(qo, kvp, kvi, lens, q, k, v, z(), z())
    np.testing.assert_array_equal(np.asarray(o_f), np.asarray(o_c))
    np.testing.assert_array_equal(
        _valid_cache_rows(kvp, kvi, lens, kc_f),
        _valid_cache_rows(kvp, kvi, lens, kc_c))
    np.testing.assert_array_equal(
        _valid_cache_rows(kvp, kvi, lens, vc_f),
        _valid_cache_rows(kvp, kvi, lens, vc_c))
    assert stats["ingest_chunks"] > 0


@pytest.mark.parametrize("window_left", [0, 17, 40])
def test_fused_vs_composed_windowed(window_left):
    lens = [40, 7, 130, 0, 65]
    qo, kvp, kvi, q, k, v = _setup(lens, seed=2)
    npages = max(int(kvp[-1]), 1)
    z = lambda: jnp.zeros((npages, HKV, PS, D), jnp.float32)
    o_f, (kc_f, vc_f), stats = _fused(
        qo, kvp, kvi, lens, q, k, v, z(), z(), window_left=window_left)
    o_c, (kc_c, vc_c) = _composed(
        qo, kvp, kvi, lens, q, k, v, z(), z(), window_left=window_left)
    np.testing.assert_array_equal(np.asarray(o_f), np.asarray(o_c))
    np.testing.assert_array_equal(
        _valid_cache_rows(kvp, kvi, lens, kc_f),
        _valid_cache_rows(kvp, kvi, lens, kc_c))


def test_write_only_units_complete_the_cache():
    """A custom mask whose first KV chunk no q row attends prunes that
    chunk from EVERY tile — it must still reach the cache via
    CODE_WRITE_ONLY units (empty row span, no MXU work)."""
    lens = [48]  # 3 chunks of 16; chunk 0's columns all-masked
    mask = np.zeros((48, 48), bool)
    for i in range(48):
        mask[i, 16 + (i % 32)] = True  # every row attends, cols <16 never
    mask_flat = mask.reshape(-1)
    qo, kvp, kvi, q, k, v = _setup(lens, seed=3)
    npages = int(kvp[-1])
    z = lambda: jnp.zeros((npages, HKV, PS, D), jnp.float32)
    plan_np = build_prefill_ingest_units(
        qo, kvp, kvi, np.asarray(lens, np.int64), block_q=BQ,
        pages_per_chunk=PPC, page_size=PS, causal=False,
        mask_flat=mask_flat)
    assert plan_np["stats"]["ingest_write_only_units"] > 0
    assert np.any(plan_np["code"] == CODE_WRITE_ONLY)
    o_f, (kc_f, vc_f), _ = _fused(qo, kvp, kvi, lens, q, k, v, z(), z(),
                                  causal=False, mask_flat=mask_flat)
    o_c, (kc_c, vc_c) = _composed(qo, kvp, kvi, lens, q, k, v, z(), z(),
                                  causal=False, mask_flat=mask_flat)
    np.testing.assert_array_equal(np.asarray(o_f), np.asarray(o_c))
    np.testing.assert_array_equal(
        _valid_cache_rows(kvp, kvi, lens, kc_f),
        _valid_cache_rows(kvp, kvi, lens, kc_c))
    np.testing.assert_array_equal(
        _valid_cache_rows(kvp, kvi, lens, vc_f),
        _valid_cache_rows(kvp, kvi, lens, vc_c))


def test_fused_vs_composed_packed_mask():
    """The packed-custom-mask rung: a random per-request bitmap (the
    MaskMode::CUSTOM form) through the in-kernel bitmap expansion."""
    lens = [40, 33]
    rng = np.random.default_rng(7)
    # keep the diagonal set so no q row attends the empty set
    mask_flat = np.concatenate(
        [((rng.random((n, n)) < 0.6) | np.eye(n, dtype=bool)).reshape(-1)
         for n in lens])
    qo, kvp, kvi, q, k, v = _setup(lens, seed=4)
    npages = int(kvp[-1])
    z = lambda: jnp.zeros((npages, HKV, PS, D), jnp.float32)
    o_f, (kc_f, _vf), _ = _fused(
        qo, kvp, kvi, lens, q, k, v, z(), z(), causal=False,
        mask_flat=mask_flat)
    o_c, (kc_c, _vc) = _composed(
        qo, kvp, kvi, lens, q, k, v, z(), z(), causal=False,
        mask_flat=mask_flat)
    np.testing.assert_array_equal(np.asarray(o_f), np.asarray(o_c))
    np.testing.assert_array_equal(
        _valid_cache_rows(kvp, kvi, lens, kc_f),
        _valid_cache_rows(kvp, kvi, lens, kc_c))


@pytest.mark.parametrize("kv_quant,cache_dtype", [
    ("int8", jnp.int8), ("fp8", jnp.float8_e4m3fn)])
def test_quantized_cache_bits_and_output(kv_quant, cache_dtype):
    """int8/fp8: cache bits == ``append_paged_kv_cache_quant_*``
    bit-for-bit on every valid row; attention output == the composed
    attend-the-codes path bitwise (same codes, same kernel walk)."""
    lens = [40, 7, 130, 0, 65]
    qo, kvp, kvi, q, k, v = _setup(lens, seed=5)
    npages = int(kvp[-1])
    ks, vs = 0.05, 0.04
    z = lambda: jnp.zeros((npages, HKV, PS, D), cache_dtype)
    o_f, (kc_f, vc_f), _ = _fused(
        qo, kvp, kvi, lens, q, k, v, z(), z(), kv_quant=kv_quant,
        ks=ks, vs=vs)
    o_c, (kc_c, vc_c) = _composed(
        qo, kvp, kvi, lens, q, k, v, z(), z(), kv_quant=kv_quant,
        ks=ks, vs=vs)
    np.testing.assert_array_equal(np.asarray(o_f), np.asarray(o_c))
    for f, c in ((kc_f, kc_c), (vc_f, vc_c)):
        np.testing.assert_array_equal(
            _valid_cache_rows(kvp, kvi, lens, f).view(np.uint8),
            _valid_cache_rows(kvp, kvi, lens, c).view(np.uint8))


def test_append_only_form_and_pos_offsets():
    """``attend=False`` (the reroute's form) with per-request position
    offsets: cache bits == the composed rotate-at-global-positions ->
    quant-append, bit-for-bit."""
    lens = [24, 9, 16]
    pos0 = [0, 8, 16]  # page-aligned global starts
    qo, kvp, kvi, _q, k, v = _setup(lens, seed=6)
    npages = int(kvp[-1])
    scale = 0.5
    z = lambda: jnp.zeros((npages, HKV, PS, D), jnp.float8_e4m3fn)
    plan_np = build_prefill_ingest_units(
        qo, kvp, kvi, np.asarray(lens, np.int64), block_q=8,
        pages_per_chunk=PPC, page_size=PS, causal=False, prune=False,
        fused_ingest={"pos_offsets": np.asarray(pos0, np.int64)})
    statics = dict(num_units=plan_np.pop("num_units"),
                   block_q=plan_np.pop("block_q"),
                   pages_per_chunk=plan_np.pop("pages_per_chunk"))
    plan_np.pop("stats")
    plan = {kk: jnp.asarray(vv) for kk, vv in plan_np.items()}
    kc_f, vc_f = fused_paged_prefill_ingest(
        None, k, v, z(), z(), plan, causal=False, attend=False,
        kv_quant="fp8", k_scale=scale, v_scale=scale, **statics)
    # composed: rotate at the GLOBAL positions, append at the local
    kv_pos, kv_req = _positions(lens)
    gpos = kv_pos + np.repeat(np.asarray(pos0), lens).astype(np.int32)
    k_rot = rotate_at_positions_static(k, jnp.asarray(gpos))
    kc_c, vc_c = append_paged_kv_cache_quant_fp8(
        k_rot, v, jnp.asarray(kv_req), jnp.asarray(kv_pos), (z(), z()),
        jnp.asarray(kvi), jnp.asarray(kvp), jnp.float32(scale),
        jnp.float32(scale), "HND")
    for f, c in ((kc_f, kc_c), (vc_f, vc_c)):
        np.testing.assert_array_equal(
            _valid_cache_rows(kvp, kvi, lens, f).view(np.uint8),
            _valid_cache_rows(kvp, kvi, lens, c).view(np.uint8))


# ---------------------------------------------------------------------------
# wrapper run_ingest
# ---------------------------------------------------------------------------


def _wrapper_setup(lens, monkeypatch, dtype=jnp.float32):
    monkeypatch.setenv("FLASHINFER_TPU_BACKEND", "pallas")
    import flashinfer_tpu as fi

    qo, kvp, kvi, q, k, v = _setup(lens, seed=8, dtype=dtype)
    last = np.asarray([n % PS or PS for n in lens], np.int32)
    w = fi.BatchPrefillWithPagedKVCacheWrapper(kv_layout="HND")
    return w, qo, kvp, kvi, last, q, k, v


@pytest.mark.quick
def test_wrapper_run_ingest_fused_vs_composed(monkeypatch):
    """``run_ingest`` with the plan static ON == OFF (the composed
    oracle through the SAME entry point), f32 bitwise."""
    lens = [40, 7, 130, 0, 65]
    w, qo, kvp, kvi, last, q, k, v = _wrapper_setup(lens, monkeypatch)
    npages = int(kvp[-1])
    z = lambda: jnp.zeros((npages, HKV, PS, D), jnp.float32)
    outs = {}
    for mode in (True, False):
        w.plan(qo, kvp, kvi, last, HQ, HKV, D, PS, causal=True,
               kv_lens=np.asarray(lens), fused_ingest=mode)
        o, (kc, vc) = w.run_ingest(q, k, v, (z(), z()))
        outs[mode] = (np.asarray(o), kc, vc)
    np.testing.assert_array_equal(outs[True][0], outs[False][0])
    np.testing.assert_array_equal(
        _valid_cache_rows(kvp, kvi, lens, outs[True][1]),
        _valid_cache_rows(kvp, kvi, lens, outs[False][1]))
    np.testing.assert_array_equal(
        _valid_cache_rows(kvp, kvi, lens, outs[True][2]),
        _valid_cache_rows(kvp, kvi, lens, outs[False][2]))


def test_wrapper_run_ingest_int8_cache_bits(monkeypatch):
    lens = [40, 33]
    w, qo, kvp, kvi, last, q, k, v = _wrapper_setup(lens, monkeypatch)
    npages = int(kvp[-1])
    z = lambda: jnp.zeros((npages, HKV, PS, D), jnp.int8)
    outs = {}
    for mode in (True, False):
        w.plan(qo, kvp, kvi, last, HQ, HKV, D, PS, causal=True,
               kv_lens=np.asarray(lens), fused_ingest=mode)
        o, (kc, vc) = w.run_ingest(q, k, v, (z(), z()),
                                   k_scale=0.05, v_scale=0.04)
        outs[mode] = (np.asarray(o), kc, vc)
    np.testing.assert_allclose(outs[True][0], outs[False][0],
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_array_equal(
        _valid_cache_rows(kvp, kvi, lens, outs[True][1]),
        _valid_cache_rows(kvp, kvi, lens, outs[False][1]))


def test_wrapper_run_ingest_errors(monkeypatch):
    lens = [40, 33]
    w, qo, kvp, kvi, last, q, k, v = _wrapper_setup(lens, monkeypatch)
    npages = int(kvp[-1])
    w.plan(qo, kvp, kvi, last, HQ, HKV, D, PS, causal=True,
           kv_lens=np.asarray(lens), fused_ingest=True)
    zi = jnp.zeros((npages, HKV, PS, D), jnp.int8)
    with pytest.raises(ValueError, match="k_scale/v_scale"):
        w.run_ingest(q, k, v, (zi, zi))
    zf = jnp.zeros((npages, HKV, PS, D), jnp.float32)
    with pytest.raises(ValueError, match="raw rows"):
        w.run_ingest(q, k[:10], v[:10], (zf, zf))
    with pytest.raises(RuntimeError, match="plan"):
        type(w)(kv_layout="HND").run_ingest(q, k, v, (zf, zf))


# ---------------------------------------------------------------------------
# rope_quantize_fp8_append_paged_kv_cache reroute
# ---------------------------------------------------------------------------


def _reroute_args(seed=0):
    # whole-page runs: page-aligned start AND end (the gate's contract
    # — a partial last page would zero rows the composed tier keeps)
    lens = np.array([24, 8, 16])
    pos0 = np.array([0, 8, 0])
    kv_indptr = np.array([0, 4, 8, 12], np.int32)
    kv_indices = np.arange(12, dtype=np.int32)
    bi = np.repeat(np.arange(3), lens).astype(np.int32)
    pos = np.concatenate(
        [np.arange(n) + p for n, p in zip(lens, pos0)]).astype(np.int32)
    T = int(lens.sum())
    key = jax.random.PRNGKey(seed)
    DD = 128  # full-head rotary at the reroute's production head_dim
    q = jax.random.normal(key, (T, HQ, DD), jnp.float32)
    k = jax.random.normal(jax.random.fold_in(key, 1), (T, HKV, DD),
                          jnp.float32)
    v = jax.random.normal(jax.random.fold_in(key, 2), (T, HKV, DD),
                          jnp.float32)
    from flashinfer_tpu.rope import generate_cos_sin_cache

    csc = generate_cos_sin_cache(64, DD)
    return q, k, v, csc, pos, kv_indices, kv_indptr, bi


@pytest.mark.quick
def test_reroute_fused_vs_composed_bitwise(monkeypatch):
    """The fused-ingest reroute writes EXACTLY the composed tier's
    cache bits and q output (the oracle stays live via the backend
    gate), and the fused kernel actually ran."""
    from flashinfer_tpu import rope as rope_mod
    from flashinfer_tpu.ops import paged_prefill as pp

    q, k, v, csc, pos, kvi, kvp, bi = _reroute_args()
    calls = []
    real = pp.fused_paged_prefill_ingest

    def spy(*a, **kw):
        calls.append(1)
        return real(*a, **kw)

    def run(backend):
        monkeypatch.setenv("FLASHINFER_TPU_BACKEND", backend)
        kc = jnp.zeros((12, HKV, PS, 128), jnp.float8_e4m3fn)
        vc = jnp.zeros((12, HKV, PS, 128), jnp.float8_e4m3fn)
        return rope_mod.rope_quantize_fp8_append_paged_kv_cache(
            q, k, None, None, v, csc, jnp.asarray(pos), (kc, vc),
            jnp.asarray(kvi), jnp.asarray(kvp), jnp.asarray(bi),
            jnp.asarray(pos), kv_layout="HND", quant_scale_q=0.4,
            quant_scale_kv=0.5)

    monkeypatch.setattr(pp, "fused_paged_prefill_ingest", spy)
    qf, (kcf, vcf) = run("pallas")
    assert calls, "geometry qualified but the reroute did not fire"
    qc, (kcc, vcc) = run("xla")  # the composed oracle tier
    np.testing.assert_array_equal(np.asarray(qf).view(np.uint8),
                                  np.asarray(qc).view(np.uint8))
    np.testing.assert_array_equal(np.asarray(kcf).view(np.uint8),
                                  np.asarray(kcc).view(np.uint8))
    np.testing.assert_array_equal(np.asarray(vcf).view(np.uint8),
                                  np.asarray(vcc).view(np.uint8))


def test_reroute_geometry_gates(monkeypatch):
    """Geometries outside the fused contract stay on the composed
    tier: NHD layout, a non-default cos/sin cache, and mid-page append
    starts must never reach the fused kernel; MLA (``v is None``)
    exits BEFORE the reroute by contract."""
    from flashinfer_tpu import rope as rope_mod
    from flashinfer_tpu.ops import paged_prefill as pp

    monkeypatch.setenv("FLASHINFER_TPU_BACKEND", "pallas")
    q, k, v, csc, pos, kvi, kvp, bi = _reroute_args()
    calls = []
    real = pp.fused_paged_prefill_ingest

    def spy(*a, **kw):
        calls.append(1)
        return real(*a, **kw)

    monkeypatch.setattr(pp, "fused_paged_prefill_ingest", spy)

    def run(layout="HND", cache=None, positions=pos):
        kc = jnp.zeros((12, HKV, PS, 128) if layout == "HND"
                       else (12, PS, HKV, 128), jnp.float8_e4m3fn)
        vc = jnp.zeros_like(kc)
        return rope_mod.rope_quantize_fp8_append_paged_kv_cache(
            q, k, None, None, v, cache if cache is not None else csc,
            jnp.asarray(pos), (kc, vc), jnp.asarray(kvi),
            jnp.asarray(kvp), jnp.asarray(bi), jnp.asarray(positions),
            kv_layout=layout, quant_scale_q=0.4, quant_scale_kv=0.5)

    run(layout="NHD")
    assert not calls  # NHD: composed
    run(cache=csc * 1.0001)
    assert not calls  # non-default cos/sin cache: composed
    shifted = pos.copy()
    shifted[:] = pos + 3  # mid-page starts
    run(positions=shifted)
    assert not calls
    # mid-page END: the whole-page write-back would zero live rows a
    # longer cached sequence still owns — must stay composed (the
    # interior re-append hazard)
    drop = np.ones(pos.shape[0], bool)
    drop[23] = False  # run 0 now ends at position 22 (mid-page)
    q2, k2, v2 = q[drop], k[drop], v[drop]
    kc = jnp.zeros((12, HKV, PS, 128), jnp.float8_e4m3fn)
    rope_mod.rope_quantize_fp8_append_paged_kv_cache(
        q2, k2, None, None, v2, csc, jnp.asarray(pos[drop]),
        (kc, jnp.zeros_like(kc)), jnp.asarray(kvi), jnp.asarray(kvp),
        jnp.asarray(bi[drop]), jnp.asarray(pos[drop]),
        kv_layout="HND", quant_scale_q=0.4, quant_scale_kv=0.5)
    assert not calls
    with pytest.raises(NotImplementedError, match="MLA"):
        rope_mod.rope_quantize_fp8_append_paged_kv_cache(
            q[:, 0], k[:, 0], None, None, None, csc, jnp.asarray(pos),
            (jnp.zeros((12, HKV, PS, 128), jnp.float8_e4m3fn),) * 2,
            jnp.asarray(kvi), jnp.asarray(kvp), jnp.asarray(bi),
            jnp.asarray(pos), kv_layout="HND")
    assert not calls  # the MLA exit precedes the reroute


# ---------------------------------------------------------------------------
# cost model chooser + acceptance bar
# ---------------------------------------------------------------------------


@pytest.mark.quick
def test_chooser_and_headline_byte_drop():
    """The ISSUE 14 acceptance bar: headline prefill shapes drop >= 20%
    of modeled HBM bytes, and the chooser prices the separate path as
    three SEQUENTIAL launches (rope/append passes cannot hide under
    the attention MXU floor) so compute-bound shapes still fuse."""
    from flashinfer_tpu.obs import costmodel, hwspec

    for tq, tkv in ((8 * 512, 8 * 4096), (8192, 8192)):
        bd = costmodel.prefill_ingest_breakdown(tq, tkv, 32, 8, 128)
        assert bd["avoided_fraction"] >= 0.20
        assert bd["separate_bytes"] == pytest.approx(
            bd["rope_bytes"] + bd["append_bytes"]
            + bd["attention_bytes"])
        for chip in ("v5e", "v5p"):
            spec = hwspec.spec(chip)
            use, ev = costmodel.predict_prefill_ingest_win(
                tq, tkv, 32, 8, 128, hbm_tbps=spec.hbm_tbps,
                peak_tflops=spec.peak_tflops("bf16"))
            assert use  # the two deleted memory passes clear the 2% bar
            assert ev["fused_s"] < ev["separate_s"]
    # a (hypothetical) chip so compute-starved the memory passes are
    # noise keeps the proven composition via the 2% bar
    use, _ = costmodel.predict_prefill_ingest_win(
        4096, 4096, 32, 8, 128, hbm_tbps=1e6, peak_tflops=1e-3)
    assert not use


def test_ingest_cost_family_stats_form():
    """costmodel.prefill_ingest: launched work from live plan stats,
    effective work the attended pairs — effective <= launched, and the
    byte side is the fused single-pass traffic."""
    from flashinfer_tpu.obs import costmodel

    lens = [64, 64]
    qo, kvp, kvi, _q, _k, _v = _setup(lens, seed=9)
    plan = build_prefill_ingest_units(
        qo, kvp, kvi, np.asarray(lens, np.int64), block_q=BQ,
        pages_per_chunk=PPC, page_size=PS, causal=True)
    c = costmodel.prefill_ingest(
        128, 128, HQ, HKV, D, stats=plan["stats"], block_q=BQ,
        pages_per_chunk=PPC, page_size=PS)
    assert c.op == "prefill_ingest"
    assert c.flops_effective <= c.flops
    alg = costmodel.prefill_ingest(128, 128, HQ, HKV, D)
    assert alg.bytes_read + alg.bytes_written == pytest.approx(
        costmodel.prefill_ingest_breakdown(
            128, 128, HQ, HKV, D)["fused_bytes"])
    # the A/B's separate-mode rows: same op family + FLOPs (the same
    # work executes, split over three launches), three-pass traffic
    sep = costmodel.prefill_ingest_separate(128, 128, HQ, HKV, D)
    assert sep.op == "prefill_ingest"
    assert sep.flops == pytest.approx(alg.flops)
    assert sep.flops_effective == pytest.approx(alg.flops_effective)
    assert sep.bytes_read + sep.bytes_written == pytest.approx(
        costmodel.prefill_ingest_breakdown(
            128, 128, HQ, HKV, D)["separate_bytes"])


# ---------------------------------------------------------------------------
# serving adoptions
# ---------------------------------------------------------------------------


@pytest.mark.quick
def test_mixed_step_ingest_token_parity(monkeypatch):
    """MixedServingStep A/B: the fused-ingest step samples the SAME
    tokens as the composed step (the engine cross-tier pin's bar), the
    eager oracle matches bitwise per mode, and continuation steps
    reject/resolve the knob correctly."""
    monkeypatch.setenv("FLASHINFER_TPU_BACKEND", "pallas")
    from flashinfer_tpu.models.llama import LlamaConfig, init_llama_params
    from flashinfer_tpu.serve.step import MixedServingStep, SamplingConfig

    cfg = LlamaConfig.tiny(num_layers=2, dtype=jnp.float32)
    params = init_llama_params(jax.random.PRNGKey(0), cfg)
    qo_lens, kv0 = [11, 5, 19], [0, 0, 0]
    ppr = 6
    npages = len(qo_lens) * ppr
    kvp = np.arange(len(qo_lens) + 1) * ppr
    kvi = np.arange(npages)
    flat = jnp.asarray(np.random.default_rng(0).integers(
        1, cfg.vocab_size, sum(qo_lens)), jnp.int32)

    def mk():
        z = lambda: jnp.zeros(
            (npages, cfg.num_kv_heads, PS, cfg.head_dim), cfg.dtype)
        return [(z(), z()) for _ in range(cfg.num_layers)]

    toks = {}
    for mode in (True, False):
        ms = MixedServingStep()
        ms.plan(cfg, qo_lens, kv0, kvp, kvi, PS,
                sampling=SamplingConfig(0.8, 7), fused_ingest=mode)
        assert ms._plan.fused_ingest is mode
        t, _lg, _cc, _ = ms.run(params, flat, mk(), jax.random.PRNGKey(3))
        t2, _, _, _ = ms.run_unfused(params, flat, mk(),
                                     jax.random.PRNGKey(3))
        np.testing.assert_array_equal(np.asarray(t), np.asarray(t2))
        toks[mode] = np.asarray(t)
    np.testing.assert_array_equal(toks[True], toks[False])
    # chunked continuations: explicit fused raises, auto resolves OFF
    ms = MixedServingStep()
    with pytest.raises(ValueError, match="from-scratch"):
        ms.plan(cfg, [4, 6, 1], [0, 2, 9], kvp, kvi, PS,
                fused_ingest=True)
    ms.plan(cfg, [4, 6, 1], [0, 2, 9], kvp, kvi, PS)
    assert ms._plan.fused_ingest is False


def test_engine_ingest_token_parity_and_trace_budget():
    """Engine kernel tier with prefill.fused_ingest on: tokens bitwise
    equal to both the composed kernel tier and the reference oracle,
    the from-scratch prefill step actually takes the ingest branch,
    and the one-trace-per-rung budget holds (the lax.cond dispatch is
    value-level, not a trace axis)."""
    import flashinfer_tpu.serve.engine_kernels as ek
    from flashinfer_tpu.models.llama import LlamaConfig, init_llama_params
    from flashinfer_tpu.serve.engine import (EngineConfig, EngineRequest,
                                             ServingEngine)

    cfg = LlamaConfig.tiny(num_layers=2, dtype=jnp.float32)
    params = init_llama_params(jax.random.PRNGKey(0), cfg)

    def run(fused, backend="kernel", spy_hits=None):
        orig = ek.build_engine_work_units
        if spy_hits is not None:
            def spy(*a, **kw):
                out = orig(*a, **kw)
                spy_hits.append(out.get("ingest_on", 0))
                return out
            ek.build_engine_work_units = spy
        try:
            ec = EngineConfig(
                num_pages=64, page_size=8, max_batch=4,
                prefill_budget_tokens=32, max_seq_tokens=64,
                attention_backend=backend, fused_ingest=fused,
                enable_prefix_cache=False)
            eng = ServingEngine(cfg, params, ec)
            for i, n in enumerate([11, 5, 19]):
                eng.submit(EngineRequest(f"r{i}", list(range(1, n + 1)),
                                         max_new_tokens=4))
            out = eng.run()
        finally:
            ek.build_engine_work_units = orig
        return {k: v for k, v in sorted(out.items())}, eng

    hits = []
    on, eng_on = run("on", spy_hits=hits)
    off, _ = run("off")
    ref, _ = run("off", backend="reference")
    assert on == off == ref
    assert sum(hits) >= 1, "no step took the ingest branch"
    assert all(n == 1 for n in eng_on._rung_traced.values())
    assert eng_on.num_traces == len(eng_on._rung_traced) <= 9


def test_engine_config_validates_ingest_knob():
    from flashinfer_tpu.models.llama import LlamaConfig, init_llama_params
    from flashinfer_tpu.serve.engine import EngineConfig, ServingEngine

    cfg = LlamaConfig.tiny(num_layers=1, dtype=jnp.float32)
    params = init_llama_params(jax.random.PRNGKey(0), cfg)
    with pytest.raises(ValueError, match="fused_ingest"):
        ServingEngine(cfg, params, EngineConfig(
            num_pages=16, fused_ingest="maybe"))


# ---------------------------------------------------------------------------
# analysis-registration skew + observability schema
# ---------------------------------------------------------------------------


@pytest.mark.quick
def test_analysis_registrations_match_real_modules():
    """The L007/L009 registrations (the PR 4 NOTE: unregistered
    surfaces are silently skipped) cannot skew from the real modules:
    planner + kernel + launcher exist with the names registered, and
    the knob is registered with the choices the configs ship."""
    from flashinfer_tpu import autotuner
    from flashinfer_tpu.analysis.pallas_contract import PLANNER_KERNELS
    from flashinfer_tpu.analysis.vmem_budget import KNOB_LAUNCHES
    from flashinfer_tpu.ops import paged_prefill as pp

    assert PLANNER_KERNELS["build_prefill_ingest_units"] == \
        "_fused_prefill_ingest_kernel"
    assert callable(getattr(pp, "build_prefill_ingest_units"))
    assert callable(getattr(pp, "_fused_prefill_ingest_kernel"))
    assert KNOB_LAUNCHES["prefill.fused_ingest"].launcher == \
        "fused_paged_prefill_ingest"
    assert callable(getattr(pp, "fused_paged_prefill_ingest"))
    spec = autotuner.KNOWN_KNOBS["prefill.fused_ingest"]
    assert spec.kind == "str" and set(spec.choices) == {"off", "on"}


def test_tuning_config_ingest_sections_valid():
    """The shipped v5e/v5p prefill_ingest seed sections are L006-valid
    against the REAL registry (key parses, knob known, value in
    choices) and stay seed-labeled until an on-chip sweep lands."""
    import json
    import os

    import flashinfer_tpu
    from flashinfer_tpu import autotuner

    cfg_dir = os.path.join(os.path.dirname(flashinfer_tpu.__file__),
                           "tuning_configs")
    for gen in ("v5e", "v5p"):
        data = json.load(open(os.path.join(cfg_dir, f"{gen}.json")))
        sec = data["prefill_ingest"]
        assert sec["seed"] is True
        assert sec["seed_keys"]
        for key, val in sec["tactics"].items():
            op = key.split("|", 1)[0]
            assert op == "prefill.fused_ingest"
            assert autotuner.validate_tactic(op, val) is None


def test_stamp_row_ingest_identity_and_measurement():
    """roofline.stamp_row: ``fused_ingest`` is an identity field (A/B
    rows never compete with banked history — the step_mode precedent)
    and ``ingest_bytes_avoided`` a measurement field the auditor
    accepts."""
    from flashinfer_tpu.obs import bench_audit, costmodel, hwspec, roofline

    cost = costmodel.prefill_ingest(512, 4096, 32, 8, 128)
    row = {"phase": "prefill", "kind": "paged"}
    roofline.stamp_row(row, cost, 1e-3, hwspec.spec("v5e"),
                       fused_ingest=True, ingest_bytes_avoided=1.5e8)
    assert row["fused_ingest"] is True
    assert row["ingest_bytes_avoided"] == 1.5e8
    assert "ingest_bytes_avoided" in bench_audit.MEASUREMENT_FIELDS
    assert "fused_ingest" not in bench_audit.MEASUREMENT_FIELDS


def test_perf_report_prefill_ingest_section():
    """obs perf (perf/6): the prefill_ingest section joins the
    predicted byte drop with stamped ingest rows, and the headline
    cells all clear the >= 20% acceptance bar."""
    from flashinfer_tpu.obs import roofline

    pred = roofline.predict_prefill_ingest()
    assert pred
    for cell in pred.values():
        assert cell["avoided_fraction"] >= 0.20
        assert cell["chips"]
    report = roofline.build_perf_report([])
    assert report["schema"].endswith("/5")
    assert "prefill_ingest" in report
    text = roofline.render_perf_report(report)
    assert "prefill-ingest" in text
