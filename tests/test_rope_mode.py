"""In-attention RoPE (pos_encoding_mode="ROPE_LLAMA") across the surface.

The reference rotates q/k inside the attention kernels from an UNROTATED
cache (decode.cuh:217, prefill kernels).  Here rotation is an elementwise
pre-pass at the plan positions — position-equivalent — so each test
checks the mode against manually rotating the inputs and running mode
NONE through the same entry point.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import flashinfer_tpu as fi
from flashinfer_tpu.rope import rotate_at_positions

RS, RT = 1.0, 1e4


def _rot(x, pos):
    return rotate_at_positions(jnp.asarray(x), jnp.asarray(pos, jnp.int32),
                               RS, RT)


@pytest.mark.parametrize("causal", [False, True])
def test_single_prefill_rope_mode(causal):
    ql, kl, H, D = 24, 56, 4, 64
    key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, (ql, H, D), jnp.float32)
    k = jax.random.normal(jax.random.fold_in(key, 1), (kl, H, D), jnp.float32)
    v = jax.random.normal(jax.random.fold_in(key, 2), (kl, H, D), jnp.float32)
    o = fi.single_prefill_with_kv_cache(
        q, k, v, causal=causal, pos_encoding_mode="ROPE_LLAMA"
    )
    ref = fi.single_prefill_with_kv_cache(
        _rot(q, np.arange(ql) + (kl - ql)), _rot(k, np.arange(kl)), v,
        causal=causal,
    )
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_ragged_wrapper_rope_mode():
    H, D = 4, 64
    qo = np.array([0, 13, 30], np.int32)
    kv = np.array([0, 29, 62], np.int32)
    key = jax.random.PRNGKey(3)
    q = jax.random.normal(key, (int(qo[-1]), H, D), jnp.float32)
    k = jax.random.normal(jax.random.fold_in(key, 1), (int(kv[-1]), H, D),
                          jnp.float32)
    v = jax.random.normal(jax.random.fold_in(key, 2), (int(kv[-1]), H, D),
                          jnp.float32)
    w = fi.BatchPrefillWithRaggedKVCacheWrapper()
    w.plan(qo, kv, H, H, D, causal=True, pos_encoding_mode="ROPE_LLAMA")
    o = np.asarray(w.run(q, k, v))
    # manual rotation at the bottom-right-aligned absolute positions
    qpos = np.concatenate([
        np.arange(qo[b + 1] - qo[b]) + ((kv[b + 1] - kv[b]) - (qo[b + 1] - qo[b]))
        for b in range(2)
    ])
    kpos = np.concatenate([np.arange(kv[b + 1] - kv[b]) for b in range(2)])
    w2 = fi.BatchPrefillWithRaggedKVCacheWrapper()
    w2.plan(qo, kv, H, H, D, causal=True)
    ref = np.asarray(w2.run(_rot(q, qpos), _rot(k, kpos), v))
    np.testing.assert_allclose(o, ref, rtol=1e-4, atol=1e-4)


def test_paged_prefill_wrapper_rope_mode():
    H, D, PS = 4, 64, 8
    qo = np.array([0, 13, 30], np.int32)
    kv_lens = [29, 33]
    pages_per = [(x + PS - 1) // PS for x in kv_lens]
    kv_pages = np.concatenate([[0], np.cumsum(pages_per)]).astype(np.int32)
    total_pages = int(kv_pages[-1])
    key = jax.random.PRNGKey(7)
    kc = jax.random.normal(key, (total_pages, H, PS, D), jnp.float32)
    vc = jax.random.normal(jax.random.fold_in(key, 1),
                           (total_pages, H, PS, D), jnp.float32)
    q = jax.random.normal(jax.random.fold_in(key, 2), (int(qo[-1]), H, D),
                          jnp.float32)
    last = np.asarray(
        [x - (p - 1) * PS for x, p in zip(kv_lens, pages_per)], np.int32
    )
    w = fi.BatchPrefillWithPagedKVCacheWrapper(kv_layout="HND")
    w.plan(qo, kv_pages, np.arange(total_pages, dtype=np.int32), last,
           H, H, D, PS, causal=True, pos_encoding_mode="ROPE_LLAMA")
    assert w._fused_plan is None  # rope forces the gather path
    o = np.asarray(w.run(q, (kc, vc)))
    # reference: rotate the CACHE rows at their in-request positions and
    # q at its absolute positions, run mode NONE
    kflat = np.asarray(jnp.swapaxes(kc, 1, 2)).reshape(-1, H, D)
    kflat_rot = kflat.copy()
    for b in range(2):
        sl = slice(int(kv_pages[b]) * PS, int(kv_pages[b]) * PS + kv_lens[b])
        kflat_rot[sl] = np.asarray(
            _rot(kflat[sl], np.arange(kv_lens[b]))
        )
    kc_rot = jnp.swapaxes(
        jnp.asarray(kflat_rot).reshape(total_pages, PS, H, D), 1, 2
    )
    qpos = np.concatenate([
        np.arange(qo[b + 1] - qo[b]) + (kv_lens[b] - (qo[b + 1] - qo[b]))
        for b in range(2)
    ])
    w2 = fi.BatchPrefillWithPagedKVCacheWrapper(kv_layout="HND")
    w2.plan(qo, kv_pages, np.arange(total_pages, dtype=np.int32), last,
            H, H, D, PS, causal=True)
    ref = np.asarray(w2.run(_rot(q, qpos), (kc_rot, vc)))
    np.testing.assert_allclose(o, ref, rtol=1e-3, atol=1e-3)


def test_batch_decode_wrapper_rope_mode():
    B, HQ, HKV, D, PS = 3, 4, 4, 64, 8
    lens = [24, 8, 17]
    pages_per = [(x + PS - 1) // PS for x in lens]
    total_pages = sum(pages_per)
    key = jax.random.PRNGKey(0)
    kc = jax.random.normal(key, (total_pages, HKV, PS, D), jnp.float32)
    vc = jax.random.normal(jax.random.fold_in(key, 1),
                           (total_pages, HKV, PS, D), jnp.float32)
    q = jax.random.normal(jax.random.fold_in(key, 2), (B, HQ, D),
                          jnp.float32)
    indptr = np.concatenate([[0], np.cumsum(pages_per)]).astype(np.int32)
    last = np.asarray([x - (p - 1) * PS for x, p in zip(lens, pages_per)],
                      np.int32)

    def make(mode):
        w = fi.BatchDecodeWithPagedKVCacheWrapper(kv_layout="HND")
        w.plan(indptr, np.arange(total_pages, dtype=np.int32), last,
               HQ, HKV, D, PS, pos_encoding_mode=mode)
        return w

    o = np.asarray(make("ROPE_LLAMA").run(q, (kc, vc)), np.float32)
    # reference: rotate cache rows by in-request position, q by len-1
    kflat = np.asarray(jnp.swapaxes(kc, 1, 2)).reshape(-1, HKV, D)
    kflat_rot = kflat.copy()
    for b in range(B):
        sl = slice(int(indptr[b]) * PS, int(indptr[b]) * PS + lens[b])
        kflat_rot[sl] = np.asarray(_rot(kflat[sl], np.arange(lens[b])))
    kc_rot = jnp.swapaxes(
        jnp.asarray(kflat_rot).reshape(total_pages, PS, HKV, D), 1, 2
    )
    q_rot = jnp.stack([
        _rot(q[b][None], [lens[b] - 1])[0] for b in range(B)
    ])
    ref = np.asarray(make("NONE").run(q_rot, (kc_rot, vc)), np.float32)
    np.testing.assert_allclose(o, ref, rtol=1e-3, atol=1e-3)
