"""Kernel dataflow analyzer passes (ISSUE 16): L014 DMA/semaphore race
detection and L015 Mosaic-lowerability lint.

Per-hazard synthetic fixtures pin each L014 check class
(read-before-wait, slot-overwrite, wait-imbalance under ``pl.when``,
cross-grid-iteration carry) and each L015 rule, and the acceptance
regressions skew the REAL kernels: deleting the fused-prefill
mainloop's wait loop / breaking its slot parity / widening its warmup
guard must flag exactly L014, un-suppressing the decode static-variant
warmup over its predecessor's in-flight prefetch must flag exactly
L014, and a new rotation-style lane slice must surface as a NEW L015
that the committed ``mosaic_risks`` budget does NOT absorb.  The
unmodified tree stays clean under both passes.
"""

import os
import textwrap

import pytest

from flashinfer_tpu import analysis
from flashinfer_tpu.analysis import dma_race, mosaic_lowering
from flashinfer_tpu.analysis.core import Project, load_source

PKG_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "flashinfer_tpu"))

OPS_PREFILL = os.path.join(PKG_ROOT, "ops", "paged_prefill.py")
OPS_DECODE = os.path.join(PKG_ROOT, "ops", "paged_decode.py")


def _project(*named_sources):
    return Project([load_source(textwrap.dedent(src), name)
                    for name, src in named_sources])


def _real(path):
    return open(path).read()


def _tags(findings):
    return sorted(f.message[1:].split("]", 1)[0] for f in findings)


# a minimal double-buffered DMA kernel scaffold the fixtures specialize
_HEADER = """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
"""

_LAUNCH = """
    def launch(x):
        return pl.pallas_call(
            _k, grid=(4,),
            in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
            out_specs=pl.BlockSpec((8, 128), lambda i: (0, 0)),
            scratch_shapes=[pltpu.VMEM((2, 8, 128), jnp.float32),
                            pltpu.SemaphoreType.DMA((2,))],
        )(x)
"""


# ------------------------------------------------ L014 check fixtures --


@pytest.mark.quick
def test_l014_read_before_wait_fixture():
    src = _HEADER + """
    def _k(x_hbm, o_ref, buf, sem):
        c = pltpu.make_async_copy(x_hbm.at[0], buf.at[0], sem.at[0])
        c.start()
        o_ref[...] = buf[0]
        c.wait()
    """ + _LAUNCH
    findings = dma_race.run(_project(("k.py", src)))
    assert [f.code for f in findings] == ["L014"], findings
    assert _tags(findings) == ["read-before-wait"]
    assert "`buf`" in findings[0].message


@pytest.mark.quick
def test_l014_slot_overwrite_fixture():
    """Second start into the same slot while the first copy may still
    be in flight — the double-buffer parity bug shape."""
    src = _HEADER + """
    def _k(x_hbm, o_ref, buf, sem):
        c0 = pltpu.make_async_copy(x_hbm.at[0], buf.at[0], sem.at[0])
        c0.start()
        c1 = pltpu.make_async_copy(x_hbm.at[1], buf.at[0], sem.at[1])
        c1.start()
        c0.wait()
        c1.wait()
        o_ref[...] = buf[0]
    """ + _LAUNCH
    findings = dma_race.run(_project(("k.py", src)))
    assert [f.code for f in findings] == ["L014"], findings
    assert _tags(findings) == ["slot-overwrite"]


@pytest.mark.quick
def test_l014_wait_imbalance_under_when_fixture():
    """Start guarded by `pl.when(u == 0)`, wait unguarded: every step
    past the first waits on a semaphore nothing signalled — the
    BENCH_r04/r05 wedge shape."""
    src = _HEADER + """
    def _k(x_hbm, o_ref, buf, sem):
        u = pl.program_id(0)
        c = pltpu.make_async_copy(x_hbm.at[0], buf.at[0], sem.at[0])

        @pl.when(u == 0)
        def _():
            c.start()

        c.wait()
        o_ref[...] = buf[0]
    """ + _LAUNCH
    findings = dma_race.run(_project(("k.py", src)))
    assert [f.code for f in findings] == ["L014"], findings
    assert _tags(findings) == ["wait-imbalance"]
    assert "`sem`" in findings[0].message


@pytest.mark.quick
def test_l014_cross_step_carry_clean_then_skewed():
    """The cross-grid prefetch pipeline: each step consumes its
    predecessor's copy and prefetches for its successor.  Correctly
    guarded it is clean; consuming only from step 2 on leaves step 0's
    prefetch in flight under step 1's start — a carry-labeled
    slot-overwrite plus a dangling DMA."""
    clean = _HEADER + """
    def _k(x_hbm, o_ref, buf, sem):
        u = pl.program_id(0)
        nu = pl.num_programs(0)

        @pl.when(u > 0)
        def _():
            pltpu.make_async_copy(
                x_hbm.at[u - 1], buf.at[0], sem.at[0]).wait()

        @pl.when(u + 1 < nu)
        def _():
            pltpu.make_async_copy(
                x_hbm.at[u], buf.at[0], sem.at[0]).start()

        o_ref[...] = x_hbm[0, 0]
    """ + _LAUNCH
    assert dma_race.run(_project(("k.py", clean))) == []

    skew = clean.replace("@pl.when(u > 0)", "@pl.when(u > 1)")
    assert skew != clean
    findings = dma_race.run(_project(("k.py", skew)))
    assert findings and all(f.code == "L014" for f in findings)
    tags = _tags(findings)
    assert "slot-overwrite" in tags and "dangling-dma" in tags
    assert any("cross-grid-iteration carry" in f.message
               for f in findings)


# --------------------------------------- L014 real-file skew probes --


@pytest.mark.quick
def test_l014_wait_deletion_skew_real_fused_prefill():
    """THE acceptance regression: delete the fused-prefill mainloop's
    KV wait loop and the work-unit pipeline reads undelivered slots at
    every step — exactly L014 (and a lot of it)."""
    real = _real(OPS_PREFILL)
    skew = real.replace(
        "    for d in kv_dmas(u, slot):\n"
        "        d.wait()\n"
        "\n"
        "    # the whole GQA group rides one MXU dot",
        "\n"
        "    # the whole GQA group rides one MXU dot")
    assert skew != real
    findings = dma_race.run(
        _project(("flashinfer_tpu/ops/paged_prefill.py", skew)))
    assert findings and all(f.code == "L014" for f in findings)
    tags = set(_tags(findings))
    assert {"read-before-wait", "dangling-dma"} <= tags, tags


def test_l014_slot_parity_skew_real_fused_prefill():
    """Prefetching the NEXT unit into the CURRENT slot (rem(u) instead
    of rem(u+1)) overwrites the buffer the mainloop is about to read."""
    real = _real(OPS_PREFILL)
    skew = real.replace(
        "        for d in kv_dmas(nxt, jax.lax.rem(u + 1, 2)):\n"
        "            d.start()",
        "        for d in kv_dmas(nxt, jax.lax.rem(u, 2)):\n"
        "            d.start()")
    assert skew != real
    findings = dma_race.run(
        _project(("flashinfer_tpu/ops/paged_prefill.py", skew)))
    assert findings and all(f.code == "L014" for f in findings)
    assert "slot-overwrite" in _tags(findings)


def test_l014_sem_balance_skew_real_fused_prefill():
    """Widening the Q warmup guard from (u == 0 AND first) to just
    (first) re-issues the unit-0 Q DMA on later steps — start/wait
    imbalance plus a dangling copy at teardown."""
    real = _real(OPS_PREFILL)
    skew = real.replace(
        "    @pl.when(jnp.logical_and(u == 0, first_ref[0] == 1))\n"
        "    def _():\n"
        "        q_dma(0, qslot_ref[0]).start()",
        "    @pl.when(first_ref[0] == 1)\n"
        "    def _():\n"
        "        q_dma(0, qslot_ref[0]).start()")
    assert skew != real
    findings = dma_race.run(
        _project(("flashinfer_tpu/ops/paged_prefill.py", skew)))
    assert findings and all(f.code == "L014" for f in findings)
    assert "dangling-dma" in _tags(findings)


def test_l014_decode_warmup_suppression_skew():
    """The static cross-step decode variant must NOT warm up when its
    predecessor already prefetched chunk 0 into slot 0.  Dropping the
    `~prev_prefetched` suppression double-starts the slot over the
    in-flight copy — the exact correlated-guard shape the simulator's
    `~`/`is` modeling exists for."""
    real = _real(OPS_DECODE)
    skew = real.replace(
        "@pl.when((num_chunks > 0) & ~prev_prefetched)",
        "@pl.when(num_chunks > 0)")
    assert skew != real
    findings = dma_race.run(
        _project(("flashinfer_tpu/ops/paged_decode.py", skew)))
    assert findings and all(f.code == "L014" for f in findings)
    fused = [f for f in findings
             if f.func == "_decode_kernel_fused_heads"]
    assert fused, findings
    tags = set(_tags(fused))
    assert {"slot-overwrite", "dangling-dma"} <= tags, tags
    assert any("cross-grid-iteration carry" in f.message for f in fused)


# ------------------------------------------------ L015 rule fixtures --


@pytest.mark.quick
def test_l015_rule_fixtures_fire_and_aligned_stays_clean():
    """One kernel per rule outcome: misaligned + strided rotation
    slices, a cast-to-match, and a dynamic gather all flag; the
    lane-aligned twin (128-bound slices, width-1 running stat, literal
    dtype cast) is clean."""
    risky = _HEADER + """
    def _k(x_ref, o_ref):
        xf = x_ref[...]
        x1, x2 = xf[:, :64], xf[:, 64:]
        e1, e2 = xf[:, 0::2], xf[:, 1::2]
        cast = xf.astype(o_ref.dtype)
        g = jnp.take(xf, jnp.argmax(xf, axis=-1), axis=0)
        o_ref[...] = x1 + x2

    def launch(x):
        return pl.pallas_call(
            _k, grid=(4,),
            in_specs=[pl.BlockSpec((8, 256), lambda i: (0, 0))],
            out_specs=pl.BlockSpec((8, 256), lambda i: (0, 0)),
        )(x)
    """
    findings = mosaic_lowering.run(_project(("k.py", risky)))
    assert all(f.code == "L015" for f in findings)
    assert _tags(findings) == ["cast", "gather", "lane-slice",
                               "lane-slice", "strided-lane",
                               "strided-lane"], findings
    # the hazard-free twin: every construct has a committed lowering
    clean = _HEADER + """
    def _k(x_ref, o_ref):
        xf = x_ref[...]
        lo, hi = xf[:, :128], xf[:, 128:]
        stat = xf[:, :1]
        o_ref[...] = (lo + hi).astype(jnp.float32) + stat

    def launch(x):
        return pl.pallas_call(
            _k, grid=(4,),
            in_specs=[pl.BlockSpec((8, 256), lambda i: (0, 0))],
            out_specs=pl.BlockSpec((8, 256), lambda i: (0, 0)),
        )(x)
    """
    assert mosaic_lowering.run(_project(("k.py", clean))) == []


@pytest.mark.quick
def test_l015_rotation_slice_probe_real_fused_prefill():
    """The PR 14 in-register rotation — `xf[:, :half]` / `[half:]` with
    half = head_dim // 2 and the stride-2 interleave — is flagged on
    the real file by L015 and ONLY L015 (L014 has nothing to say about
    a lowering risk)."""
    project = _project(
        ("flashinfer_tpu/ops/paged_prefill.py", _real(OPS_PREFILL)))
    findings = mosaic_lowering.run(project)
    rot = [f for f in findings
           if f.func == "_fused_prefill_ingest_kernel"
           and f.message[1:].split("]")[0] in ("lane-slice",
                                               "strided-lane")]
    assert len(rot) == 4, findings  # both halves + both interleaves
    assert all(f.code == "L015" for f in rot)
    assert any("not provably 0 mod 128" in f.message for f in rot)


def test_l015_new_rotation_slice_not_absorbed_by_baseline():
    """A NEW unaligned lane slice in an already-triaged kernel must
    overflow the committed ``mosaic_risks`` budget and surface as a new
    finding — triage counts cannot silently absorb fresh risks."""
    real = _real(OPS_PREFILL)
    skew = real.replace(
        "            x1, x2 = xf[:, :half], xf[:, half:]",
        "            x1, x2 = xf[:, :half], xf[:, half:]\n"
        "            x2 = x2 + xf[:, 8:]")
    assert skew != real
    findings = mosaic_lowering.run(
        _project(("flashinfer_tpu/ops/paged_prefill.py", skew)))
    new, _old, _stale = analysis.partition_against_baseline(
        findings, analysis.load_baseline())
    assert len(new) == 1 and new[0].code == "L015", new


# ------------------------------------------- clean-tree pins + stats --


def test_l014_whole_tree_clean_no_baseline_involved():
    """The shipped kernels have NO DMA/semaphore findings at the pass
    level — L014 runs baseline-free (a race is fixed, never triaged)."""
    project = Project.from_paths([PKG_ROOT])
    assert dma_race.run(project) == []
    st = dma_race.stats(project)
    assert st["kernels_skipped"] == 0, st
    assert st["kernels_analyzed"] >= 7, st


def test_l015_whole_tree_matches_committed_mosaic_risks():
    """Every current L015 finding is covered by the committed
    ``mosaic_risks`` triage (no new, no stale) — the bring-up checklist
    is exactly in sync with the tree."""
    project = Project.from_paths([PKG_ROOT])
    findings = mosaic_lowering.run(project)
    suppressed = []
    for f in findings:
        sf = next((s for s in project.files
                   if s.path == f.filename), None)
        if sf is not None and sf.suppression_for(f.line):
            continue
        suppressed.append(f)
    new, _old, stale = analysis.partition_against_baseline(
        suppressed, {k: v for k, v in analysis.load_baseline().items()
                     if k[0] == "L015"})
    assert new == [], new
    assert stale == [], stale
    st = mosaic_lowering.stats(project)
    assert st["kernels_linted"] >= 17, st
    assert st["findings_by_rule"]["lane-slice"] >= 3, st
    assert st["findings_by_rule"]["strided-lane"] >= 2, st


def test_l014_l015_stats_feed_doctor_counts():
    """`obs doctor` renders analyzed-vs-skipped kernel counts from the
    pass stats hooks — pin the schema both sides read."""
    project = Project.from_paths([os.path.join(PKG_ROOT, "ops")])
    d = dma_race.stats(project)
    for key in ("kernels_analyzed", "kernels_skipped", "kernels_no_dma",
                "sites_unresolved", "skip_reasons"):
        assert key in d, d
    m = mosaic_lowering.stats(project)
    for key in ("kernels_linted", "sites_unresolved",
                "findings_by_rule"):
        assert key in m, m
    assert set(m["findings_by_rule"]) == set(mosaic_lowering.RULES)
