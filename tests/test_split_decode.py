"""Split-KV decode: interpret-mode parity + plan/selection contracts.

ISSUE 6 acceptance suite for the split-KV paged-decode path
(``ops/paged_decode.py`` ``build_decode_split_units`` /
``_decode_split_kernel_fused_heads`` / ``paged_decode_attention_split``):

- split-vs-unsplit parity across S in {1, 2, 4, 8} x {GQA,
  quantized-KV (int8 + fp8), ragged page counts, single-page requests}
  — both kernel-level and through the wrapper plan/run lifecycle;
- the online-softmax merge identity pinned against ``merge_states``
  (partial states computed by the UNSPLIT kernel over disjoint KV
  spans must merge to the full answer — the algebra the split kernel's
  reduction stands on);
- plan-time selection: ``choose_decode_splits`` picks S>1 for the
  bs=256/ctx=512-class cliff shapes and S=1 for long-context shapes
  (the cost-model pin the acceptance criteria name), the L009
  VMEM-feasibility evaluator prices the split launch, and the
  ``plan.decode_splits`` obs counter records every selection;
- the cost model's chunk formula never skews from the kernel's (the
  two are deliberately duplicated across the jax-free import
  boundary).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import flashinfer_tpu as fi
from flashinfer_tpu.obs import costmodel
from flashinfer_tpu.ops.merge import merge_states
from flashinfer_tpu.ops.paged_decode import (
    build_decode_split_units,
    decode_split_tactic_key,
    paged_decode_attention,
    paged_decode_attention_split,
    split_pages_per_chunk,
)

SPLITS = (1, 2, 4, 8)


def _paged_inputs(kv_lens, HKV, D, PS, cache_dtype=jnp.bfloat16, seed=0):
    """Padded rectangular page table + HND caches for ragged kv_lens,
    pages permuted so split spans never alias contiguous memory."""
    kv_lens = np.asarray(kv_lens, np.int64)
    B = len(kv_lens)
    pages_r = -(-kv_lens // PS)
    P = max(int(pages_r.max(initial=1)), 1)
    npages = int(pages_r.sum()) + 1
    key = jax.random.PRNGKey(seed)
    kc = jax.random.normal(
        key, (npages, HKV, PS, D), jnp.float32).astype(cache_dtype)
    vc = jax.random.normal(
        jax.random.fold_in(key, 1),
        (npages, HKV, PS, D), jnp.float32).astype(cache_dtype)
    rng = np.random.default_rng(seed)
    perm = rng.permutation(np.arange(1, npages)).astype(np.int32)
    pt = np.zeros((B, P), np.int32)
    nxt = 0
    for b in range(B):
        for j in range(int(pages_r[b])):
            pt[b, j] = perm[nxt]
            nxt += 1
    return pt, kv_lens, kc, vc


def _run_split(q, kc, vc, pt, kv_lens, S, **kw):
    ppc = split_pages_per_chunk(
        kc.shape[2], kc.shape[1], kc.shape[3],
        np.dtype(kc.dtype).itemsize)
    plan = build_decode_split_units(
        pt, kv_lens, num_splits=S, page_size=kc.shape[2],
        pages_per_chunk=ppc)
    statics = dict(
        num_units=plan.pop("num_units"),
        num_splits=plan.pop("num_splits"),
        single_chunk=plan.pop("single_chunk"),
        pages_per_chunk=plan.pop("pages_per_chunk"),
    )
    stats = plan.pop("stats")
    plan = {k: jnp.asarray(v) for k, v in plan.items()}
    out = paged_decode_attention_split(q, kc, vc, plan, **statics, **kw)
    return out, stats


CASES = {
    # name: (kv_lens, HQ, HKV, D, cache dtype)
    "gqa": ([512, 480, 129, 512], 8, 2, 64, jnp.bfloat16),
    "quant_int8": ([512, 480, 129, 512], 8, 2, 64, jnp.int8),
    "quant_fp8": ([512, 480, 129, 512], 8, 2, 64, jnp.float8_e4m3fn),
    "ragged": ([513, 17, 256, 300], 4, 4, 64, jnp.bfloat16),
    "single_page": ([16, 512, 1, 7], 8, 2, 64, jnp.bfloat16),
}


@pytest.mark.parametrize("case", sorted(CASES))
@pytest.mark.parametrize("S", SPLITS)
def test_split_vs_unsplit_kernel_parity(case, S):
    """The tentpole pin: the partial-state kernel + merge_states
    reduction matches the unsplit fused-heads kernel for every split
    factor, including quantized caches, ragged page lists, and
    single-page requests (empty-unit handling)."""
    kv_lens, HQ, HKV, D, cdt = CASES[case]
    PS = 16
    pt, lens, kc, vc = _paged_inputs(kv_lens, HKV, D, PS, cdt)
    q = jax.random.normal(
        jax.random.PRNGKey(7), (len(kv_lens), HQ, D), jnp.bfloat16)
    sm = D ** -0.5
    ref, ref_lse = paged_decode_attention(
        q, kc, vc, jnp.asarray(pt), jnp.asarray(lens.astype(np.int32)),
        sm_scale=sm, kv_layout="HND", return_lse=True)
    (out, lse), _stats = _run_split(
        q, kc, vc, pt, lens, S, sm_scale=sm, return_lse=True)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        atol=0.02, rtol=0.02)
    np.testing.assert_allclose(
        np.asarray(lse), np.asarray(ref_lse), atol=1e-2, rtol=1e-3)


@pytest.mark.quick
def test_split_kernel_quick():
    """Quick-tier representative of the split kernel surface: both
    pipeline variants (single-chunk cross-unit prefetch via S=4, the
    general multi-chunk path via S=2 over a long request) against the
    unsplit kernel."""
    PS = 16
    pt, lens, kc, vc = _paged_inputs([1024, 33, 512], 2, 64, PS)
    q = jax.random.normal(jax.random.PRNGKey(3), (3, 8, 64), jnp.bfloat16)
    sm = 0.125
    ref = paged_decode_attention(
        q, kc, vc, jnp.asarray(pt), jnp.asarray(lens.astype(np.int32)),
        sm_scale=sm, kv_layout="HND")
    for S, want_single in ((4, True), (2, False)):
        (out), stats = _run_split(q, kc, vc, pt, lens, S, sm_scale=sm)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32),
            atol=0.02, rtol=0.02)
        assert (stats["max_chunks_per_unit"] <= 1) == want_single


@pytest.mark.parametrize("S", (2, 4))
def test_wrapper_split_parity(S):
    """plan(num_splits=S)/run matches the unsplit wrapper bit-for-
    tolerance through the full lifecycle (padded batch buckets, scale
    folding, LSE return)."""
    PS = 16
    kv_lens = [512, 480, 129, 512, 77]
    B, HQ, HKV, D = len(kv_lens), 8, 2, 64
    pages_r = np.array([-(-l // PS) for l in kv_lens])
    indptr = np.concatenate([[0], np.cumsum(pages_r)]).astype(np.int32)
    npages = int(pages_r.sum())
    indices = np.random.default_rng(0).permutation(npages).astype(np.int32)
    last = np.array([(l - 1) % PS + 1 for l in kv_lens], np.int32)
    key = jax.random.PRNGKey(0)
    kc = jax.random.normal(key, (npages, HKV, PS, D), jnp.bfloat16)
    vc = jax.random.normal(
        jax.random.fold_in(key, 1), (npages, HKV, PS, D), jnp.bfloat16)
    q = jax.random.normal(
        jax.random.fold_in(key, 2), (B, HQ, D), jnp.bfloat16)

    def run(s):
        w = fi.BatchDecodeWithPagedKVCacheWrapper(kv_layout="HND")
        w.plan(indptr, indices, last, HQ, HKV, D, PS, num_splits=s)
        return w, w.run_return_lse(q, (kc, vc), v_scale=0.5)

    w1, (ref, ref_lse) = run(1)
    ws, (out, lse) = run(S)
    assert w1._plan.num_splits == 1
    assert ws._plan.num_splits == S
    assert ws._plan.split_units == ws._plan.page_table.shape[0] * S
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        atol=0.02, rtol=0.02)
    np.testing.assert_allclose(
        np.asarray(lse), np.asarray(ref_lse), atol=1e-2, rtol=1e-3)


def test_merge_identity_pinned_against_merge_states():
    """The algebra the split path stands on: UNSPLIT kernel partials
    over disjoint KV spans, merged by ``merge_states``, equal the
    full-range answer (reference recursive_attention.rst identity;
    cascade.cuh:214 MergeStates)."""
    PS, B, HQ, HKV, D = 16, 2, 8, 2, 64
    ctx = 512
    pt, lens, kc, vc = _paged_inputs([ctx] * B, HKV, D, PS)
    q = jax.random.normal(jax.random.PRNGKey(5), (B, HQ, D), jnp.bfloat16)
    sm = D ** -0.5
    full, full_lse = paged_decode_attention(
        q, kc, vc, jnp.asarray(pt), jnp.asarray(lens.astype(np.int32)),
        sm_scale=sm, kv_layout="HND", return_lse=True)
    # two disjoint half-spans computed by the same unsplit kernel
    half_pages = (ctx // PS) // 2
    parts = []
    for lo, hi in ((0, half_pages), (half_pages, ctx // PS)):
        sub_pt = pt[:, lo:hi]
        sub_lens = np.full((B,), (hi - lo) * PS, np.int32)
        v, s = paged_decode_attention(
            q, kc, vc, jnp.asarray(sub_pt), jnp.asarray(sub_lens),
            sm_scale=sm, kv_layout="HND", return_lse=True)
        parts.append((v, s))
    v_st = jnp.stack([p[0] for p in parts], axis=1)  # [B, 2, HQ, D]
    s_st = jnp.stack([p[1] for p in parts], axis=1)  # [B, 2, HQ]
    merged_v, merged_s = merge_states(v_st, s_st)
    np.testing.assert_allclose(
        np.asarray(merged_v, np.float32), np.asarray(full, np.float32),
        atol=0.02, rtol=0.02)
    np.testing.assert_allclose(
        np.asarray(merged_s), np.asarray(full_lse), atol=1e-2, rtol=1e-3)


# ---------------------------------------------------------------------------
# plan-time selection: the cost-model pins the acceptance criteria name
# ---------------------------------------------------------------------------


@pytest.mark.quick
def test_choose_decode_splits_short_vs_long_context():
    """S>1 for bs=256/ctx=512-class shapes (the VERDICT cliff cells),
    S=1 for long-context shapes — the plan-time inversion of the cost
    model, at the v5e roofline the seeds were derived at."""
    bw = 0.819
    for bs, ctx in ((256, 512), (64, 512), (256, 256)):
        best, table = costmodel.choose_decode_splits(
            bs, ctx, 32, 8, 128, hbm_tbps=bw)
        assert best > 1, (bs, ctx, table)
    for bs, ctx in ((64, 4096), (64, 8192), (1, 8192), (16, 2048)):
        best, table = costmodel.choose_decode_splits(
            bs, ctx, 32, 8, 128, hbm_tbps=bw)
        assert best == 1, (bs, ctx, table)
    # the chooser honors the feasibility pruner: rejecting every S>1
    # forces the unsplit path even on cliff shapes
    best, _ = costmodel.choose_decode_splits(
        256, 512, 32, 8, 128, hbm_tbps=bw, feasible=lambda s: False)
    assert best == 1


def test_decode_split_cost_model_terms():
    """decode_split cost: S=1 degenerates to paged_decode exactly; S>1
    adds the f32 partial-state merge traffic on both sides of the
    HBM bus and counts launched (chunk-padded) vs effective FLOPs."""
    base = costmodel.paged_decode(64, 512, 32, 8, 128)
    s1 = costmodel.decode_split(64, 512, 32, 8, 128, num_splits=1)
    assert s1.flops == base.flops
    assert s1.bytes_total == base.bytes_total
    assert s1.op == "decode_split"

    s2 = costmodel.decode_split(64, 512, 32, 8, 128, num_splits=2)
    bd = costmodel.decode_split_breakdown(
        64, 512, 32, 8, 128, num_splits=2)
    assert bd["merge_bytes"] > 0
    # partial out+lse written once, read back once by the merge
    assert s2.bytes_written == pytest.approx(
        bd["merge_bytes"] / 2 + bd["out_bytes"])
    assert s2.bytes_read == pytest.approx(
        bd["kv_bytes"] + bd["q_bytes"] + bd["merge_bytes"] / 2)
    assert s2.effective_flops == pytest.approx(
        costmodel.attention(1, 512, 32, 8, 128, batch=64).flops)
    # sub-chunk split degenerates: same real partition as S=2, more
    # empty-unit merge traffic — the chooser's tie rule prefers S=2
    bd8 = costmodel.decode_split_breakdown(
        64, 512, 32, 8, 128, num_splits=8)
    assert bd8["units_real"] == bd["units_real"] == 2
    assert bd8["merge_bytes"] > bd["merge_bytes"]


def test_split_chunk_pages_matches_kernel_formula():
    """The jax-free cost-model copy of the chunk formula must never
    skew from the kernel's (plan geometry and cost geometry are the
    same physical walk)."""
    for ps in (4, 8, 16, 32):
        for hkv in (1, 2, 8, 16):
            for d in (64, 128, 256):
                for itemsize in (1, 2, 4):
                    assert costmodel.split_chunk_pages(
                        ps, hkv, d, itemsize) == split_pages_per_chunk(
                        ps, hkv, d, itemsize), (ps, hkv, d, itemsize)


def test_planner_geometry_and_contract_keys():
    """build_decode_split_units: chunk-aligned spans, split-major unit
    order, empty-unit accounting, the single-chunk certificate, and
    exactly the five scalar-prefetch plan keys the kernel launch
    consumes (the L007 planner/kernel contract)."""
    PS, ppc = 16, 4
    pt = np.arange(24, dtype=np.int32).reshape(3, 8)
    lens = np.array([128, 36, 0])
    plan = build_decode_split_units(
        pt, lens, num_splits=2, page_size=PS, pages_per_chunk=ppc)
    assert plan["num_units"] == 6 and plan["num_splits"] == 2
    # request 0: 8 pages -> per=4 -> two real units of 64 tokens
    assert list(plan["wu_page0"][:2]) == [0, 4]
    assert list(plan["wu_kvlen"][:2]) == [64, 64]
    # request 1: 3 pages -> per=ceil(2/ppc)*ppc=4 -> unit 1 empty
    assert list(plan["wu_kvlen"][2:4]) == [36, 0]
    # request 2 (pad row): both units empty, page0 forced to 0
    assert list(plan["wu_kvlen"][4:]) == [0, 0]
    assert list(plan["wu_page0"][4:]) == [0, 0]
    assert plan["single_chunk"] is True
    assert plan["stats"]["units_empty"] == 3
    launch_keys = ("pages", "kvlen", "wu_req", "wu_page0", "wu_kvlen")
    assert all(k in plan for k in launch_keys)

    # a span wider than one chunk flips the certificate off
    plan2 = build_decode_split_units(
        pt, lens, num_splits=1, page_size=PS, pages_per_chunk=ppc)
    assert plan2["single_chunk"] is False
    assert plan2["stats"]["max_chunks_per_unit"] == 2


def test_l009_evaluator_prices_the_split_launch():
    """The decode.splits knob launch binding resolves against the real
    kernel source and prices the double-buffered chunk scratch — the
    feasibility gate plan-time selection composes with."""
    from flashinfer_tpu.analysis.core import Project
    from flashinfer_tpu.analysis.vmem_budget import (KNOB_LAUNCHES,
                                                     _estimate)
    from flashinfer_tpu.ops import paged_decode as pd

    project = Project.from_paths([os.path.dirname(pd.__file__)])
    key = decode_split_tactic_key(256, 32, 32, 8, 128, 16, 16,
                                  "bfloat16")
    est = _estimate(project, KNOB_LAUNCHES["decode.splits"], 2,
                    [str(f) for f in key])
    assert est is not None
    total, _budget, launcher = est
    # k+v scratch: 2 bufs x 2 slots x ppc=16 x Hkv=8 x PS=16 x D=128 at
    # the 1-byte lower-bound itemsize = 1 MiB, plus the double-buffered
    # q/out/lse blocks at the key's declared bf16 — a real, bounded price
    assert 1_000_000 < total < 4_000_000, total
    assert launcher.name == "paged_decode_attention_split"

    from flashinfer_tpu.decode import _split_vmem_feasible
    assert _split_vmem_feasible(2, key) is True


def test_plan_rejects_unhonorable_explicit_splits():
    """An explicit num_splits>1 on a non-eligible plan (NHD layout /
    dense pos-encoding routes) raises instead of silently running the
    unsplit path."""
    PS, B = 16, 2
    indptr = np.arange(B + 1, dtype=np.int32) * 4
    indices = np.arange(B * 4, dtype=np.int32)
    last = np.full((B,), PS, np.int32)
    w = fi.BatchDecodeWithPagedKVCacheWrapper(kv_layout="NHD")
    with pytest.raises(ValueError, match="num_splits"):
        w.plan(indptr, indices, last, 8, 2, 64, PS, num_splits=2)
    w2 = fi.BatchDecodeWithPagedKVCacheWrapper(kv_layout="HND")
    with pytest.raises(ValueError, match="num_splits"):
        w2.plan(indptr, indices, last, 8, 2, 64, PS,
                pos_encoding_mode="ALIBI", num_splits=2)
    # NHD + explicit 1 (or None) stays fine
    w.plan(indptr, indices, last, 8, 2, 64, PS, num_splits=1)
    assert w._plan.num_splits == 1


def test_plan_decode_splits_counter(monkeypatch):
    """Every HND decode plan records its split selection in the
    plan.decode_splits counter (wrapper + splits labels)."""
    monkeypatch.setenv("FLASHINFER_TPU_METRICS", "1")
    from flashinfer_tpu import obs

    obs.reset()
    PS, B, HQ, HKV, D = 16, 2, 8, 2, 64
    ppr = 4
    indptr = np.arange(B + 1, dtype=np.int32) * ppr
    indices = np.arange(B * ppr, dtype=np.int32)
    last = np.full((B,), PS, np.int32)
    w = fi.BatchDecodeWithPagedKVCacheWrapper(kv_layout="HND")
    w.plan(indptr, indices, last, HQ, HKV, D, PS, num_splits=2)
    w.plan(indptr, indices, last, HQ, HKV, D, PS, num_splits=1)
    snap = obs.snapshot()
    c = snap["counters"]["plan.decode_splits"]
    key = "{splits=%s,wrapper=BatchDecodeWithPagedKVCacheWrapper}"
    assert c[key % 2] == 1
    assert c[key % 1] == 1


@pytest.mark.quick
def test_stamp_row_split_metadata_and_audit():
    """stamp_row carries the split metadata; the quality auditor treats
    merge_bytes as a derived measurement (never identity) while
    num_splits keeps rows at different factors from competing."""
    from flashinfer_tpu.obs import bench_audit, hwspec, roofline

    cost = costmodel.decode_split(256, 512, 32, 8, 128, num_splits=2)
    bd = costmodel.decode_split_breakdown(256, 512, 32, 8, 128,
                                          num_splits=2)
    row = roofline.stamp_row(
        dict(phase="decode_splits", bs=256, ctx=512, us=900.0,
             tbps=0.66),
        cost, 900e-6, hwspec.spec("v5e"),
        num_splits=2, merge_bytes=bd["merge_bytes"])
    assert row["num_splits"] == 2
    assert row["merge_bytes"] == bd["merge_bytes"]
    assert 0 < row["pct_roofline"] <= 1.05
    assert "merge_bytes" in bench_audit.MEASUREMENT_FIELDS
    assert "num_splits" not in bench_audit.MEASUREMENT_FIELDS
    auditor = bench_audit.RowAuditor([row])
    s2 = auditor.stamp(dict(row, us=1000.0, tbps=0.6))
    assert s2["quality"] == "ok"
    # a different split factor is a different configuration: its row
    # never competes with the S=2 history
    s8 = auditor.stamp(dict(row, num_splits=8, tbps=0.1))
    assert s8["quality"] == "ok"
    # stamped rows are self-describing for obs perf
    rec = costmodel.cost_from_stamped_row(row)
    assert rec is not None and rec[0].flops == cost.flops
