"""MLA attention tests vs an eager compressed-KV reference (mirrors
reference tests/attention/test_deepseek_mla.py strategy)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import flashinfer_tpu as fi
from flashinfer_tpu.mla import BatchMLAPagedAttentionWrapper


def _mla_ref(q_nope, q_pe, ckv, kpe, sm_scale, causal=False, qo_len=None):
    """Eager MLA for one request: ckv/kpe [kv_len, d]; q [qo, H, d]."""
    qn = np.asarray(q_nope, np.float32)
    qp = np.asarray(q_pe, np.float32)
    c = np.asarray(ckv, np.float32)
    p = np.asarray(kpe, np.float32)
    s = (np.einsum("qhd,kd->hqk", qn, c) + np.einsum("qhd,kd->hqk", qp, p)) * sm_scale
    qo, kv = qn.shape[0], c.shape[0]
    if causal:
        mask = np.arange(kv)[None, :] <= np.arange(qo)[:, None] + (kv - qo)
        s = np.where(mask[None], s, -1e30)
    m = s.max(-1, keepdims=True)
    e = np.exp(s - m)
    if causal:
        e = np.where(mask[None], e, 0)
    out = np.einsum("hqk,kd->qhd", e / e.sum(-1, keepdims=True), c)
    return out


def _setup_cache(key, num_pages, ps, d_ckv, d_kpe, dtype=jnp.float32):
    ckv = jax.random.normal(key, (num_pages, ps, d_ckv), dtype)
    kpe = jax.random.normal(jax.random.fold_in(key, 1), (num_pages, ps, d_kpe), dtype)
    return ckv, kpe


@pytest.mark.quick
@pytest.mark.parametrize("backend", ["pallas", "xla"])
def test_mla_decode(backend):
    B, H, d_ckv, d_kpe, PS = 3, 16, 128, 64, 8
    kv_lens = [19, 40, 3]
    num_pages = 32
    sm = 1 / np.sqrt(d_ckv + d_kpe)
    rng = np.random.default_rng(0)
    pages_per = [-(-l // PS) for l in kv_lens]
    kv_indptr = np.concatenate([[0], np.cumsum(pages_per)]).astype(np.int32)
    indices = rng.permutation(num_pages)[: kv_indptr[-1]].astype(np.int32)
    qo_indptr = np.arange(B + 1, dtype=np.int32)

    ckv, kpe = _setup_cache(jax.random.PRNGKey(0), num_pages, PS, d_ckv, d_kpe)
    q_nope = jax.random.normal(jax.random.PRNGKey(1), (B, H, d_ckv), jnp.float32)
    q_pe = jax.random.normal(jax.random.PRNGKey(2), (B, H, d_kpe), jnp.float32)

    w = BatchMLAPagedAttentionWrapper(backend=backend)
    w.plan(qo_indptr, kv_indptr, indices, np.array(kv_lens), H, d_ckv, d_kpe, PS)
    out, lse = w.run(q_nope, q_pe, ckv, kpe, return_lse=True)

    crows = np.asarray(ckv).reshape(-1, d_ckv)
    prows = np.asarray(kpe).reshape(-1, d_kpe)
    for b in range(B):
        pages = indices[kv_indptr[b] : kv_indptr[b + 1]]
        tok = np.arange(kv_lens[b])
        rows = pages[tok // PS] * PS + tok % PS
        ref = _mla_ref(q_nope[b : b + 1], q_pe[b : b + 1], crows[rows], prows[rows], sm)
        np.testing.assert_allclose(
            np.asarray(out[b]), ref[0], rtol=2e-3, atol=2e-3, err_msg=f"req {b}"
        )


def test_mla_decode_packed_layout():
    """Packed single-buffer kernel variant (one concatenated score dot)
    matches the split-layout kernel and the eager oracle bit-for-spec."""
    from flashinfer_tpu.ops.mla_decode import mla_paged_decode_attention

    B, H, d_ckv, d_kpe, PS = 3, 16, 128, 64, 8
    kv_lens = np.array([19, 40, 3], np.int32)
    num_pages = 32
    sm = 1 / np.sqrt(d_ckv + d_kpe)
    rng = np.random.default_rng(0)
    max_pages = int(-(-kv_lens.max() // PS))
    table = rng.permutation(num_pages)[: B * max_pages].astype(
        np.int32).reshape(B, max_pages)

    ckv, kpe = _setup_cache(jax.random.PRNGKey(0), num_pages, PS, d_ckv, d_kpe)
    q_nope = jax.random.normal(jax.random.PRNGKey(1), (B, H, d_ckv), jnp.float32)
    q_pe = jax.random.normal(jax.random.PRNGKey(2), (B, H, d_kpe), jnp.float32)

    kw = dict(sm_scale=float(sm), return_lse=True)
    o_s, lse_s = mla_paged_decode_attention(
        q_nope, q_pe, ckv, kpe, jnp.asarray(table), jnp.asarray(kv_lens),
        layout="split", **kw)
    o_p, lse_p = mla_paged_decode_attention(
        q_nope, q_pe, ckv, kpe, jnp.asarray(table), jnp.asarray(kv_lens),
        layout="packed", **kw)
    np.testing.assert_allclose(np.asarray(o_p), np.asarray(o_s),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(lse_p), np.asarray(lse_s),
                               rtol=1e-5, atol=1e-5)

    crows = np.asarray(ckv).reshape(-1, d_ckv)
    prows = np.asarray(kpe).reshape(-1, d_kpe)
    for b in range(B):
        tok = np.arange(kv_lens[b])
        rows = table[b][tok // PS] * PS + tok % PS
        ref = _mla_ref(q_nope[b:b+1], q_pe[b:b+1], crows[rows], prows[rows], sm)
        np.testing.assert_allclose(
            np.asarray(o_p[b]), ref[0], rtol=2e-3, atol=2e-3,
            err_msg=f"req {b}")

    with pytest.raises(ValueError, match="layout"):
        mla_paged_decode_attention(
            q_nope, q_pe, ckv, kpe, jnp.asarray(table),
            jnp.asarray(kv_lens), layout="bogus", **kw)


@pytest.mark.parametrize("backend", ["pallas", "xla"])
def test_mla_ragged_multitoken(backend):
    """Speculative multi-token qo (qo_len 3) exercises the ragged path."""
    B, H, d_ckv, d_kpe, PS = 2, 8, 64, 32, 8
    kv_lens = [24, 17]
    qo_lens = [3, 3]
    num_pages = 16
    sm = 1 / np.sqrt(d_ckv + d_kpe)
    rng = np.random.default_rng(1)
    pages_per = [-(-l // PS) for l in kv_lens]
    kv_indptr = np.concatenate([[0], np.cumsum(pages_per)]).astype(np.int32)
    indices = rng.permutation(num_pages)[: kv_indptr[-1]].astype(np.int32)
    qo_indptr = np.concatenate([[0], np.cumsum(qo_lens)]).astype(np.int32)

    ckv, kpe = _setup_cache(jax.random.PRNGKey(3), num_pages, PS, d_ckv, d_kpe)
    tq = int(qo_indptr[-1])
    q_nope = jax.random.normal(jax.random.PRNGKey(4), (tq, H, d_ckv), jnp.float32)
    q_pe = jax.random.normal(jax.random.PRNGKey(5), (tq, H, d_kpe), jnp.float32)

    w = BatchMLAPagedAttentionWrapper(backend=backend)
    w.plan(qo_indptr, kv_indptr, indices, np.array(kv_lens), H, d_ckv, d_kpe,
           PS, causal=True)
    out = w.run(q_nope, q_pe, ckv, kpe)

    crows = np.asarray(ckv).reshape(-1, d_ckv)
    prows = np.asarray(kpe).reshape(-1, d_kpe)
    for b in range(B):
        qs, qe = qo_indptr[b], qo_indptr[b + 1]
        pages = indices[kv_indptr[b] : kv_indptr[b + 1]]
        tok = np.arange(kv_lens[b])
        rows = pages[tok // PS] * PS + tok % PS
        ref = _mla_ref(
            q_nope[qs:qe], q_pe[qs:qe], crows[rows], prows[rows], sm, causal=True
        )
        np.testing.assert_allclose(
            np.asarray(out[qs:qe]), ref, rtol=2e-3, atol=2e-3, err_msg=f"req {b}"
        )


def test_mla_append_cache_roundtrip():
    """append_paged_mla_kv_cache -> wrapper decode consistency."""
    B, H, d_ckv, d_kpe, PS = 2, 4, 32, 16, 4
    num_pages = 8
    ckv = jnp.zeros((num_pages, PS, d_ckv))
    kpe = jnp.zeros((num_pages, PS, d_kpe))
    kv_lens = np.array([5, 3], np.int32)
    kv_indptr = np.array([0, 2, 3], np.int32)
    indices = np.array([4, 1, 6], np.int32)
    nnz = int(kv_lens.sum())
    append_indptr = jnp.array([0, 5, 8], jnp.int32)
    bi, pos = fi.get_batch_indices_positions(
        append_indptr, jnp.asarray(kv_lens), nnz
    )
    ckv_data = jax.random.normal(jax.random.PRNGKey(0), (nnz, d_ckv))
    kpe_data = jax.random.normal(jax.random.PRNGKey(1), (nnz, d_kpe))
    ckv, kpe = fi.append_paged_mla_kv_cache(
        ckv_data, kpe_data, bi, pos, ckv, kpe, jnp.asarray(indices),
        jnp.asarray(kv_indptr),
    )
    q_nope = jax.random.normal(jax.random.PRNGKey(2), (B, H, d_ckv))
    q_pe = jax.random.normal(jax.random.PRNGKey(3), (B, H, d_kpe))
    w = BatchMLAPagedAttentionWrapper(backend="xla")
    w.plan(np.arange(B + 1), kv_indptr, indices, kv_lens, H, d_ckv, d_kpe, PS)
    out = w.run(q_nope, q_pe, ckv, kpe)
    sm = 1 / np.sqrt(d_ckv + d_kpe)
    ref0 = _mla_ref(
        q_nope[0:1], q_pe[0:1], np.asarray(ckv_data[:5]), np.asarray(kpe_data[:5]), sm
    )
    np.testing.assert_allclose(np.asarray(out[0]), ref0[0], rtol=2e-3, atol=2e-3)


def test_mla_padded_kpe_cache_layout():
    """TPU-native kpe cache (lane-padded to 128): append writes the first 64
    columns, decode matches the 64-wide reference layout bit-for-bit."""
    import flashinfer_tpu.page as page
    from flashinfer_tpu.ops.mla_decode import (
        mla_paged_decode_attention, xla_mla_paged_decode,
    )

    B, H, d_ckv, d_kpe, PS = 2, 8, 128, 64, 8
    n_pages = 8
    key = jax.random.PRNGKey(0)
    ckv = jax.random.normal(key, (n_pages, PS, d_ckv), jnp.float32)
    kpe64 = jax.random.normal(jax.random.fold_in(key, 1), (n_pages, PS, d_kpe))
    kpe128 = jnp.pad(kpe64, ((0, 0), (0, 0), (0, 128 - d_kpe)))
    qn = jax.random.normal(jax.random.fold_in(key, 2), (B, H, d_ckv))
    qp = jax.random.normal(jax.random.fold_in(key, 3), (B, H, d_kpe))
    pt = jnp.arange(8, dtype=jnp.int32).reshape(B, 4)
    lens = jnp.array([20, 9], jnp.int32)
    sm = 1.0 / np.sqrt(d_ckv + d_kpe)

    o64 = mla_paged_decode_attention(qn, qp, ckv, kpe64, pt, lens, sm_scale=sm)
    o128 = mla_paged_decode_attention(qn, qp, ckv, kpe128, pt, lens, sm_scale=sm)
    ref = xla_mla_paged_decode(qn, qp, ckv, kpe64, pt, lens, sm_scale=sm)
    np.testing.assert_allclose(np.asarray(o64), np.asarray(ref), rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(o128), np.asarray(o64), rtol=1e-6, atol=1e-6)

    # append into the padded layout touches only the first d_kpe columns
    nnz = 5
    new_ckv = jax.random.normal(jax.random.fold_in(key, 4), (nnz, d_ckv))
    new_kpe = jax.random.normal(jax.random.fold_in(key, 5), (nnz, d_kpe))
    bi = jnp.zeros((nnz,), jnp.int32)
    pos = jnp.arange(nnz, dtype=jnp.int32)
    kv_indices = jnp.arange(8, dtype=jnp.int32)
    kv_indptr = jnp.array([0, 4, 8], jnp.int32)
    _, kpe_out = page.append_paged_mla_kv_cache(
        new_ckv, new_kpe, bi, pos, ckv, kpe128, kv_indices, kv_indptr)
    assert kpe_out.shape == kpe128.shape
    np.testing.assert_allclose(
        np.asarray(kpe_out[0, :nnz, :d_kpe]), np.asarray(new_kpe), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(kpe_out[..., d_kpe:]), 0.0)
