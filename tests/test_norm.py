"""Norm op correctness vs eager numpy references.

Mirrors the reference test pattern (tests/norm/): build inputs, run op,
compare to an eager fp32 reference with tolerances."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import flashinfer_tpu as fi


def ref_rmsnorm(x, w, eps, bias=0.0):
    x = np.asarray(x, np.float32)
    w = np.asarray(w, np.float32) + bias
    var = (x * x).mean(-1, keepdims=True)
    return x / np.sqrt(var + eps) * w


@pytest.mark.quick
@pytest.mark.parametrize("batch", [1, 19, 128])
@pytest.mark.parametrize("hidden", [128, 4096])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("backend", ["pallas", "xla"])
def test_rmsnorm(batch, hidden, dtype, backend):
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (batch, hidden), dtype)
    w = jax.random.normal(jax.random.PRNGKey(1), (hidden,), dtype)
    out = fi.rmsnorm(x, w, eps=1e-6, backend=backend)
    ref = ref_rmsnorm(x, w, 1e-6)
    tol = 2e-2 if dtype == jnp.bfloat16 else 1e-5
    np.testing.assert_allclose(np.asarray(out, np.float32), ref, rtol=tol, atol=tol)


@pytest.mark.parametrize("backend", ["pallas", "xla"])
def test_gemma_rmsnorm(backend):
    x = jax.random.normal(jax.random.PRNGKey(0), (16, 256), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(1), (256,), jnp.float32)
    out = fi.gemma_rmsnorm(x, w, backend=backend)
    ref = ref_rmsnorm(x, w, 1e-6, bias=1.0)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("backend", ["pallas", "xla"])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_fused_add_rmsnorm(backend, dtype):
    x = jax.random.normal(jax.random.PRNGKey(0), (32, 512), dtype)
    r = jax.random.normal(jax.random.PRNGKey(1), (32, 512), dtype)
    w = jax.random.normal(jax.random.PRNGKey(2), (512,), dtype)
    out, new_r = fi.fused_add_rmsnorm(x, r, w, backend=backend)
    s = np.asarray(x, np.float32) + np.asarray(r, np.float32)
    ref = ref_rmsnorm(s, w, 1e-6)
    tol = 3e-2 if dtype == jnp.bfloat16 else 1e-5
    np.testing.assert_allclose(np.asarray(new_r, np.float32), s, rtol=tol, atol=tol)
    np.testing.assert_allclose(np.asarray(out, np.float32), ref, rtol=tol, atol=tol)


def test_layernorm():
    x = jax.random.normal(jax.random.PRNGKey(0), (8, 128), jnp.float32)
    g = jax.random.normal(jax.random.PRNGKey(1), (128,), jnp.float32)
    b = jax.random.normal(jax.random.PRNGKey(2), (128,), jnp.float32)
    out = fi.layernorm(x, g, b)
    xn = np.asarray(x)
    ref = (xn - xn.mean(-1, keepdims=True)) / np.sqrt(
        xn.var(-1, keepdims=True) + 1e-5
    ) * np.asarray(g) + np.asarray(b)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-4)
