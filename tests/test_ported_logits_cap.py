"""Migration proof #11: mechanical port of the reference test file
``/root/reference/tests/attention/test_logits_cap.py`` run against
``flashinfer_tpu``.

Same porting contract as tests/test_ported_batch_prefill.py: reference
matrices verbatim — incl. the 33001-kv decode cells (run: decode work
is small) and the 31111-kv prefill cells (work-cap-gated on CPU CI) —
reference call sequences
(``single_{decode,prefill}_with_kv_cache(..., logits_soft_cap=)``),
torch.float16 -> jnp.float16.  Oracle = the reference's
``attention_logits_soft_cap_torch`` (tanh capping applied after the
1/sqrt(d) scale) in f64 numpy.  The warmup_jit CUDA prebuild fixture is
dropped (XLA compiles on first call).
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import flashinfer_tpu as fi
from tests.test_ported_batch_prefill import _sample, _work_gate


def _soft_cap_attention(q, k, v, soft_cap):
    """Reference oracle (test_logits_cap.py:66-72, non-causal as in the
    reference) in f64: scores -> cap * tanh(scores / cap) -> softmax."""
    q = np.asarray(q, np.float64)
    k = np.asarray(k, np.float64)
    v = np.asarray(v, np.float64)
    scores = np.einsum("qhd,khd->qkh", q, k) / math.sqrt(q.shape[-1])
    scores = soft_cap * np.tanh(scores / soft_cap)
    m_ = scores.max(1, keepdims=True)
    e = np.exp(scores - m_)
    attn = e / e.sum(1, keepdims=True)
    return np.einsum("qkh,khd->qhd", attn, v)


@pytest.mark.parametrize(
    "seq_len,num_heads,head_dim,soft_cap",
    _sample(
        "cap_decode",
        [1, 9, 81, 729, 33001], [4, 8, 32], [128, 256], [1.0, 30.0, 50.0],
        # always keep a long-context decode cell (runs: decode work is
        # within the CPU cap; the 31111-kv PREFILL cells are what gate)
        specials=((0, 33001),),
    ),
)
def test_single_decode_logits_soft_cap(seq_len, num_heads, head_dim,
                                       soft_cap):
    """Reference test_single_decode_logits_soft_cap (test_logits_cap.py:75)."""
    _work_gate(1, 1, seq_len, num_heads, head_dim)
    key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, (num_heads, head_dim), jnp.float16)
    k = jax.random.normal(
        jax.random.fold_in(key, 1), (seq_len, num_heads, head_dim),
        jnp.float16)
    v = jax.random.normal(
        jax.random.fold_in(key, 2), (seq_len, num_heads, head_dim),
        jnp.float16)
    o = fi.single_decode_with_kv_cache(q, k, v, logits_soft_cap=soft_cap)
    o_ref = _soft_cap_attention(
        np.asarray(q, np.float32)[None], k, v, soft_cap)[0]
    np.testing.assert_allclose(
        np.asarray(o, np.float32), o_ref, rtol=1e-3, atol=1e-3)


@pytest.mark.parametrize(
    "q_len,kv_len,num_heads,head_dim,soft_cap",
    _sample(
        "cap_prefill",
        [1, 17, 81, 987], [1, 17, 81, 987, 31111], [4, 8, 32], [128, 256],
        [1.0, 30.0, 50.0],
        specials=((1, 31111),),
    ),
)
def test_single_prefill_logits_soft_cap(q_len, kv_len, num_heads, head_dim,
                                        soft_cap):
    """Reference test_single_prefill_logits_soft_cap
    (test_logits_cap.py:93); non-causal, as in the reference."""
    _work_gate(1, q_len, kv_len, num_heads, head_dim)
    key = jax.random.PRNGKey(1)
    q = jax.random.normal(key, (q_len, num_heads, head_dim), jnp.float16)
    k = jax.random.normal(
        jax.random.fold_in(key, 1), (kv_len, num_heads, head_dim),
        jnp.float16)
    v = jax.random.normal(
        jax.random.fold_in(key, 2), (kv_len, num_heads, head_dim),
        jnp.float16)
    o = fi.single_prefill_with_kv_cache(q, k, v, logits_soft_cap=soft_cap)
    o_ref = _soft_cap_attention(q, k, v, soft_cap)
    np.testing.assert_allclose(
        np.asarray(o, np.float32), o_ref, rtol=1e-2, atol=1e-2)
